"""Continuous-batching TPU generation engine.

This is the subsystem the reference *does not have*: it streams someone else's
tokens over HTTP (Ollama `/api/chat` NDJSON → SSE transform,
`core/internal/api/handlers.go:2427-2587`). Here the decode hot loop runs
in-process on TPU and the API layer streams tokens straight out of it.

Design (SURVEY.md §7 "hard parts"):

  - **Slots**: the engine owns a static-shape KV cache of `max_slots`
    sequences. The reference's per-device concurrency cap
    (`handlers.go:212-246`) maps to free slots in this batch.
  - **Continuous batching**: requests join/leave the running batch at chunk
    boundaries; one jitted decode step serves all active slots.
  - **Chunked dispatch**: decode runs `decode_chunk` steps per device call via
    `lax.scan`, so the [K, B] token block is the only per-chunk host sync —
    dispatch overhead is amortized K×, while SSE streaming granularity stays
    at K tokens.
  - **Bucketed prefill**: prompts pad to power-of-two buckets; each bucket
    compiles once. Prompt KV inserts into the slot via a donated
    dynamic-update — no cache copies.
  - **On-device sampling**: logits never leave HBM (ops/sampling.py).
  - **Sharding**: with a mesh, params/cache shard per parallel/sharding.py
    (TP over ICI); the engine code is identical on 1 chip and N chips.

Threading: one engine thread owns the device loop; requests arrive on a
queue; each request streams tokens out through its own `queue.Queue`, which
the aiohttp layer bridges to SSE without head-of-line blocking.
"""

from __future__ import annotations

import base64
import logging
import os
import queue
import tempfile
import threading
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..kernels.attention import (
    pallas_supported,
    ragged_prefill_max_tokens,
    resolve_attn_impl,
    resolve_decode_impl,
    resolve_ragged_impl,
)
from ..utils.faults import maybe_fail
from ..models.configs import ModelConfig, resolve_config
from ..models.weights import load_llama_checkpoint
from ..models.llama import (
    init_llama_params,
    init_kv_cache,
    llama_prefill,
    llama_prefill_chunk_batch,
    llama_prefill_chunk_ragged,
    llama_decode_step,
    quantize_kv,
)
from .. import constrain
from ..ops.sampling import apply_token_mask, sample_tokens, spec_verify
from ..parallel.sharding import (
    llama_param_specs, kv_cache_specs, kv_pool_specs, shard_pytree,
    supports_ragged_prefill,
)
from ..routing import prefix as prefix_fp
from ..telemetry import perf
from ..telemetry import recorder as flight
from ..telemetry import tracing
from ..telemetry import workload
from .common import fine_bucket, pow2_bucket
from .dispatch import DispatchBackend, GSPMDBackend, LocalArraysBackend
from .drafter import NGramDrafter
from .memory import (
    KVPool,
    KVSnapshot,
    RESTORE_AGING_TTFT_MULT,
    bucket_len,
    pytree_nbytes,
)
from . import migration
from .paging import PagedKVManager
from .physical import PhysicalPool, pool_like
from .scheduler import TokenBudgetScheduler, parse_tenant_quotas
from .tokenizer import ByteTokenizer, Tokenizer, load_tokenizer
from ..utils.locks import OrderedLock

log = logging.getLogger("engine")

_DONE = object()


def _tree2(fn, a, b):
    """Apply fn(leaf_a, leaf_b) through the cache's dict nesting ({} is the
    fused int8 layout's live placeholder, not absence)."""
    if isinstance(a, dict):
        if not a:
            return {}
        return {k: _tree2(fn, a[k], b[k]) for k in a}
    return fn(a, b)


def _cow_block_raw(ck, cv, pk, pv, slot, blk, prow):
    """Physical copy-on-write: copy ONE prefix-pool block (pool row `prow`)
    into a slot's arena at block index `blk` — the boundary block of an
    unaligned prefix hit. Whole-block always (the suffix prefill overwrites
    the tail past the stored length), so there is exactly one executable no
    matter where inside the block the prefix ends."""

    def one(arena, pool):
        z = (0,) * (arena.ndim - 4)
        bt = pool.shape[3]
        seg = jax.lax.dynamic_slice(
            pool, (0, prow, 0, 0) + z,
            (pool.shape[0], 1, pool.shape[2], bt) + pool.shape[4:],
        )
        return jax.lax.dynamic_update_slice(
            arena, seg.astype(arena.dtype), (0, slot, 0, blk * bt) + z
        )

    return _tree2(one, ck, pk), _tree2(one, cv, pv)


_cow_block_fn = partial(jax.jit, donate_argnums=(0, 1))(_cow_block_raw)


def _pool_put_arena_raw(pk, pv, ck, cv, row, off, prow):
    """Prefix store: copy one block of arena KV (slot row `row`, token
    offset `off`) into pool row `prow`."""

    def one(pool, arena):
        z = (0,) * (arena.ndim - 4)
        bt = pool.shape[3]
        seg = jax.lax.dynamic_slice(
            arena, (0, row, 0, off) + z,
            (arena.shape[0], 1, arena.shape[2], bt) + arena.shape[4:],
        )
        return jax.lax.dynamic_update_slice(
            pool, seg.astype(pool.dtype), (0, prow, 0, 0) + z
        )

    return _tree2(one, pk, ck), _tree2(one, pv, cv)


_pool_put_arena_fn = partial(jax.jit, donate_argnums=(0, 1))(_pool_put_arena_raw)


def _pool_put_pool_raw(pk, pv, src_row, dst_row):
    """Prefix store when the storing slot's block itself resolves to the
    pool (a sharer storing a longer prefix): pool-row → pool-row copy."""

    def one(pool, _):
        z = (0,) * (pool.ndim - 4)
        seg = jax.lax.dynamic_slice(
            pool, (0, src_row, 0, 0) + z,
            (pool.shape[0], 1, pool.shape[2], pool.shape[3]) + pool.shape[4:],
        )
        return jax.lax.dynamic_update_slice(pool, seg, (0, dst_row, 0, 0) + z)

    return _tree2(one, pk, pk), _tree2(one, pv, pv)


_pool_put_pool_fn = partial(jax.jit, donate_argnums=(0, 1))(_pool_put_pool_raw)


def _pool_put_host_raw(pk, pv, hk, hv, prow):
    """Remote prefix import: upload ONE wire-decoded host block (shaped
    [L, 1, heads, block_tokens, *rest], zero-padded past the chain's
    tail) into pool row `prow`. Block-shaped on purpose: one executable
    regardless of the imported chain's length."""

    def one(pool, blk):
        z = (0,) * (pool.ndim - 4)
        return jax.lax.dynamic_update_slice(
            pool, blk.astype(pool.dtype), (0, prow, 0, 0) + z
        )

    return _tree2(one, pk, hk), _tree2(one, pv, hv)


_pool_put_host_fn = partial(jax.jit, donate_argnums=(0, 1))(_pool_put_host_raw)


def _host_block(x, off: int, bt: int):
    """Slice one block [off, off+bt) of a wire-decoded host KV tree on the
    token axis, zero-padding a short tail to block shape (the pad is dead:
    admission COWs the boundary block and the suffix prefill overwrites
    past the stored length). Dict-aware ({} = fused-int8 live sentinel)."""
    if isinstance(x, dict):
        if not x:
            return {}
        return {k: _host_block(v, off, bt) for k, v in x.items()}
    seg = x[:, :, :, off : off + bt]
    if seg.shape[3] < bt:
        pad = [(0, 0)] * seg.ndim
        pad[3] = (0, bt - seg.shape[3])
        seg = np.pad(seg, pad)
    return np.ascontiguousarray(seg)


def _has_safetensors(weights_dir: str) -> bool:
    return bool(weights_dir) and os.path.isdir(weights_dir) and any(
        f.endswith(".safetensors") for f in os.listdir(weights_dir)
    )


@dataclass
class GenRequest:
    prompt_ids: list[int]
    max_tokens: int = 256
    temperature: float = 0.7
    top_k: int = 0
    top_p: float = 1.0
    stop: list[str] = field(default_factory=list)
    # KV-pool preemption rank (memory.py): higher survives longer. Only read
    # when TPU_KV_HOST_OFFLOAD is on; 0 keeps every request equal.
    priority: int = 0
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    # filled by the engine
    out: "queue.Queue[Any]" = field(default_factory=queue.Queue)
    created_at: float = field(default_factory=time.time)
    # tracing: wire context captured on the submitting thread; the engine
    # loop records admit/prefill/decode child spans against it retroactively
    # (the loop thread never blocks on the tracer)
    trace_ctx: str = ""
    admitted_at: float = 0.0  # stamped when the loop pops the request
    # KV migration (migration.py): export this request's KV the moment its
    # prefill lands, instead of decoding here — the disaggregated-mode
    # handoff (TPU_ROLE=prefill). Only read when TPU_MIGRATE is on.
    migrate_after_prefill: bool = False
    # hop count: how many times this request has already been re-homed.
    # The coordinator refuses to move a request twice — without the cap a
    # drain can ping-pong the queue head between two engines whose headroom
    # recovers alternately, and the bounced request starves.
    migrations: int = 0
    # latency waterfall (telemetry/workload.py): admission-shed backoff the
    # client spent before this submit landed. Stamped by the serving layer
    # (bench clients, api handlers) — the engine only ever reads it.
    shed_wait_s: float = 0.0
    # Tenancy (model zoo): the API-key-derived tenant id this request bills
    # against. "" (the default) is unmetered — per-tenant quotas, goodput
    # ledgers, and SLO-debt preemption all key off a non-empty value, so
    # single-tenant serving never touches any of that machinery.
    tenant: str = ""
    # Grammar-constrained decoding (constrain/): the constraint spec dict
    # ({"type": "json_schema"|"json_object"|"regex"|"choice", ...}) and the
    # parsed logit_bias pairs [(token_id, bias), ...]. None/None means
    # unconstrained — the request never touches the constrain subsystem.
    constraint: dict | None = None
    logit_bias: list | None = None
    # engine-filled: the compiled per-request SlotAutomaton, attached when
    # the loop pops the request (so the FIRST sampled token is already
    # masked) and handed to the slot at activation. Never set when
    # TPU_CONSTRAIN=0.
    cn: Any = None


@dataclass
class _Slot:
    req: GenRequest
    generated: int = 0
    text: str = ""
    pending: bytes = b""
    prompt_len: int = 0
    first_token_at: float = 0.0
    # lifecycle flags for the pipelined decode loop: emission of a round can
    # run AFTER the slot's table entry was freed (fast finish-scan) or
    # errored (abort) — both must stop any later deferred emission for this
    # request (the consumer already received its terminal event)
    done: bool = False
    aborted: bool = False
    # self-speculative decoding: the slot's n-gram index over its own token
    # history (drafter.py), fed by _process_token; None when TPU_SPEC=0
    spec: Any = None
    # constrained decoding: the request's SlotAutomaton cursor (constrain/
    # masks.py), advanced by _process_token on every emitted token. None for
    # unconstrained requests and always None when TPU_CONSTRAIN=0 — the
    # loop's cn_active/active split keys off this field.
    cn: Any = None
    spec_drafted: int = 0  # draft tokens proposed for this request
    spec_accepted: int = 0  # draft tokens accepted by verify
    # KV pool: last emission wall time, the "idle" preemption policy's
    # victim signal. Only stamped when the pool is on (hot-path no-op rule).
    last_emit: float = 0.0
    # Paged KV: when admitted off a prefix-cache hit, the entry and its
    # stored length — a preemption of this slot snapshots only the rows
    # past shared_len (the shared blocks stay pinned in the paging ledger
    # and restore re-inserts them from the entry's device arrays).
    shared_entry: Any = None
    shared_len: int = 0
    # perf observatory (telemetry/perf.py): wall of this slot's previous
    # emission (anchor for the next round's inter-token gap) + lifetime
    # ITL accumulation, folded into the decode span and goodput ledger
    # at finish
    perf_last_emit: float = 0.0
    itl_s_total: float = 0.0
    itl_samples: int = 0
    # latency waterfall (telemetry/workload.py): synchronous prefill
    # dispatch wall attributed to this request (token-share of each batch /
    # chunk dispatch), inter-token gap beyond the stall threshold, and wall
    # spent parked off-slot by preemption. _finish_slot clamps these into
    # an exact partition of the request's measured wall.
    prefill_compute_s: float = 0.0
    stall_s: float = 0.0
    preempted_s: float = 0.0


@dataclass
class _DispatchedRound:
    """A decode round in flight on device: dispatched, not yet fetched.
    `entries` pins (slot index, slot OBJECT, out column) at dispatch time —
    by fetch time the table entry may hold None or a different request, and
    identity decides whether the column's tokens still belong to anyone."""

    out: Any  # device array [K, Ba] (un-fetched)
    entries: list  # [(b, _Slot, col)]
    base: Any  # np lengths snapshot at dispatch
    t0: float
    rid: int = 0  # monotonic round id (slot-reuse cooling fence)
    prefill_tokens: int = 0  # fused chunk-group tokens (scheduler cost attribution)
    prefill_padded: int = 0  # dispatched token shape incl. pads (pad-waste EMA)


@dataclass
class _PendingRound:
    """A fetched decode round awaiting (deferred) emission."""

    out: Any  # np [K, Ba]
    entries: list  # [(b, _Slot, col)]
    base: Any


@dataclass
class _PrefillState:
    """A slot whose prompt is mid-way through chunked prefill. The slot is
    reserved (not decodable, not free) until the last chunk lands."""

    req: GenRequest
    ids: list[int]
    done: int = 0  # tokens already written into the cache
    # terminal error already delivered by the stall watchdog — activation
    # and chunk failure paths must not double-publish
    aborted: bool = False
    # Paged KV: prefix-cache hit provenance, carried through the chunked
    # suffix prefill onto the activated _Slot (see _start_cached)
    shared_entry: Any = None
    shared_len: int = 0
    # latency waterfall: prefill dispatch wall accumulated while this
    # prompt was mid-chunk (token-share of each group dispatch), copied
    # onto the activated _Slot's prefill_compute_s
    prefill_s: float = 0.0


@dataclass
class _PrefillGroup:
    """A staged chunked-prefill group: up to admit_batch mid-prefill slots'
    next chunks sharing (bucket, skey), total valid tokens bounded by the
    token-budget scheduler. Dispatched either FUSED into a decode round
    (fused_step_fn — the stall-free path) or standalone when no decode rows
    are active (pure-prefill window, back-to-back)."""

    metas: list  # [(slot, _PrefillState, n)] — n = valid tokens this chunk
    tokens: Any  # np [Ab, bucket] (ragged: np [T] packed token buffer)
    slots_arr: Any  # np [Ab] (ragged: np [R])
    starts_arr: Any  # np [Ab] (ragged: np [R])
    nv_arr: Any  # np [Ab] (ragged: np [R])
    bucket: int  # ragged: the packed buffer length T
    skey: int
    n_tokens: int  # total valid tokens staged (≤ the round's budget)
    # dispatch-plane group id: once dispatched, the group's boundary logits
    # ([Ab, V]; ragged [R, V]) park on the op-owned _x_logits[gid] until the
    # activation sample ("bsample") pops them
    gid: int = 0
    # Ragged packed descriptors (tentpole path — _stage_ragged_group). metas
    # row i ↔ descriptor row i, so finish/fail indexing is shared with the
    # bucketed path.
    ragged: bool = False
    rowids_arr: Any = None  # np [T] — row id per packed token (pads = R)
    positions_arr: Any = None  # np [T] — cache position (pads = max_seq_len)
    last_idx_arr: Any = None  # np [R] — packed index of each row's last token


class GenerationEngine:
    def __init__(
        self,
        model: str | ModelConfig = "tiny-llm",
        *,
        mesh=None,
        params: Any = None,
        tokenizer: Tokenizer | None = None,
        max_slots: int = 8,
        max_seq_len: int = 512,
        dtype: Any = jnp.bfloat16,
        seed: int = 0,
        decode_chunk: int = 4,
        weights_dir: str = "",
        quant: str = "",
        kv_quant: str = "",
        prefill_chunk: int = 512,
        admit_batch: int = 4,
        decode_compact: str = "auto",
        prompt_cache_mb: int = 256,
        prefill_buckets: str = "fine",
        prefill_boost: float = 2.0,
        target_ttft_ms: float = 2000.0,
        backend: DispatchBackend | None = None,
    ):
        # a config.json beside the weights is authoritative: any supported-
        # family checkpoint serves without a catalog entry (models/configs.py
        # resolve_config — the reference's serve-any-name parity,
        # discovery.go:482-560)
        self.cfg = resolve_config(model, weights_dir)
        self.mesh = mesh
        # Dispatch plane (dispatch.py): every device mutation the loop makes
        # goes through ONE funnel (_dx) that forwards the (op, payload) step
        # to the backend before executing it locally. LocalArraysBackend is
        # a no-op (today's single-process path, zero overhead); GSPMDBackend
        # serializes the step-program to follower processes so the SAME op
        # closures replay there — multi-controller JAX requires every
        # process to execute every device computation in the same order.
        self._backend = backend if backend is not None else LocalArraysBackend()
        self._spmd = bool(self._backend.spmd)
        if self._spmd and mesh is None:
            raise ValueError("a GSPMD dispatch backend requires a mesh")
        # non-empty = the dispatch plane died with this error. Under a GSPMD
        # backend a poisoned dispatch cannot be recovered (followers already
        # executed the step; re-initializing device state is not replayable),
        # so the engine goes dead instead of rebuilding (_recover_cache).
        self.dead: str = ""
        self._dead_lock = threading.Lock()  # atomizes submit vs death
        if self._spmd:
            from jax.sharding import NamedSharding, PartitionSpec

            # identity jit with a replicated out_sharding: the reshard that
            # turns a host array (or a sharded global) into a fully-
            # replicated global every process can device_get locally
            self._repl_sharding = NamedSharding(mesh, PartitionSpec())
            self._put_repl = jax.jit(
                lambda x: x, out_shardings=self._repl_sharding
            )
        self.dtype = dtype
        self.max_slots = max_slots
        self.max_seq_len = max_seq_len
        self.decode_chunk = decode_chunk
        # admission prompt buckets: "fine" adds 1.5x midpoint rungs between
        # the pow2 sizes (common.py:fine_bucket) — ~12% mean pad waste in
        # the prefill weight pass instead of ~25%, for one extra executable
        # per octave ("pow2" opts out)
        self.prefill_fine = (prefill_buckets or "fine").lower() != "pow2"
        self.tokenizer: Tokenizer = tokenizer or load_tokenizer(weights_dir)

        hd = self.cfg.resolved_head_dim
        # Prefill and decode resolve separately: flash-prefill is a real win
        # (no O(S²) score materialization) while decode is fastest on the
        # fused XLA einsum path — see kernels/attention.py:resolve_decode_impl.
        self.attn_impl = (
            resolve_attn_impl(mesh) if pallas_supported(max_seq_len, hd) else "xla"
        )

        # weight-only int8 (TPU_QUANT=int8 via Config.tpu_quant): decode is
        # weight-bandwidth bound, so halving weight bytes ≈ halves step time
        # (models/quant.py)
        self.quant = quant
        if self.quant and self.quant != "int8":
            log.warning("unknown quant mode %r (supported: int8); serving unquantized",
                        self.quant)
            self.quant = ""
        # int8 KV cache (TPU_KV_QUANT=int8): once weights are int8, decode
        # becomes cache-bandwidth bound — halving KV bytes buys another
        # ~25-40% step time at 8B and doubles the (slots × context) that
        # fits beside the weights. Reads route through the s8-MXU pallas
        # kernel (kernels/attention.py:decode_attend_q8).
        self.kv_quant = kv_quant
        if self.kv_quant and self.kv_quant != "int8":
            log.warning("unknown kv_quant mode %r (supported: int8); using %s cache",
                        self.kv_quant, jnp.dtype(dtype).name)
            self.kv_quant = ""
        if self.cfg.kv_lora_rank:
            # MLA (models/mla.py): chunked prefill runs the absorbed form
            # against the latent cache (models/mla.py:
            # mla_prefill_chunk_batch) — long prompts interleave with decode
            # rounds and the prompt-prefix KV cache applies, exactly as for
            # the GQA families. int8 latents (kv_quant=int8): ~7x fewer
            # cache bytes than bf16 GQA K/V; decode runs the s8-MXU kernel
            # (kernels/attention.py:decode_attend_q8_mla) — whole-S tiles
            # at serving context lengths, blocked HBM streaming with a
            # dynamic trip count past its VMEM budget (S=32k included);
            # the XLA dequant-then-dot path remains only for cache lengths
            # no 128-multiple block divides.
            if self.kv_quant:
                log.info(
                    "MLA int8 latents: ~2x context capacity vs bf16 "
                    "latents; s8-MXU decode kernel (whole-S at serving "
                    "lengths, blocked streaming at long context)"
                )
        self.decode_impl = resolve_decode_impl(
            mesh,
            quantized=self.kv_quant == "int8",
            seq_len=max_seq_len,
            head_dim=hd,
            n_kv_heads=self.cfg.n_kv_heads,
            n_heads=self.cfg.n_heads,
        )
        # Slot compaction: decode rounds dispatch only the ACTIVE rows
        # (pow2-bucketed) instead of the full max_slots batch — the weights
        # pass, sampling, and (on the kernels' scalar-prefetch indirection)
        # cache traffic all scale with occupancy instead of capacity. "auto"
        # enables it for the int8 cache (whose kernels take slot_ids);
        # "on" forces it for bf16 too (xla gather path), "off" disables.
        # ("auto" stays single-chip: under a mesh the compact batch's dynamic
        # row gathers would cut across the dp/tp cache sharding — XLA inserts
        # collectives per layer and the "optimization" inverts. "on" overrides
        # for configs whose mesh doesn't shard the slot axis.)
        dc = (decode_compact or "auto").lower()
        if dc not in ("auto", "on", "off"):
            log.warning("unknown decode_compact mode %r (auto|on|off); using auto", dc)
            dc = "auto"
        single_chip = mesh is None or mesh.size == 1
        self.decode_compact = dc == "on" or (
            dc == "auto" and self.kv_quant == "int8" and single_chip
        )
        # chunked prefill: bound the per-iteration prefill work so admissions
        # interleave with decode rounds (0 disables; sp prefill is whole-prompt
        # by design — the sp axis itself bounds per-chip work)
        self.prefill_chunk = max(0, prefill_chunk)
        # batched admission: up to admit_batch short prompts prefill in ONE
        # dispatch — at 8B the prompt weight pass dominates admission cost,
        # and a starved admission path caps how many slots ever decode
        # (measured: 102 tok/s vs 1.8k+ at B=64 with per-request prefill)
        self.admit_batch = max(1, admit_batch)
        # Token-budget scheduler (scheduler.py): prefill rides INSIDE decode
        # rounds under a per-round token budget self-tuned from measured
        # per-token prefill vs decode-round cost, clamped so the oldest
        # mid-prefill prompt still activates within target_ttft_ms. Replaces
        # the retired wall-clock alternation (last decode time ×
        # TPU_PREFILL_BOOST) that let prefill monopolize the loop on a
        # locally-attached chip. `prefill_boost` is accepted-and-ignored so
        # existing construction sites keep working.
        del prefill_boost
        self.target_ttft_ms = max(1.0, float(target_ttft_ms))
        self._sched = TokenBudgetScheduler(
            target_ttft_ms=self.target_ttft_ms,
            min_budget=min(64, self.prefill_chunk) if self.prefill_chunk else 1,
            tenant_quotas=parse_tenant_quotas(
                os.environ.get("TPU_TENANT_QUOTAS", "")
            ),
        )
        self._last_active_n = 0  # decode rows in the most recent dispatch

        pspecs = llama_param_specs(self.cfg)
        if self.quant == "int8":
            from ..models.quant import quantized_specs

            pspecs = quantized_specs(pspecs)
        cspecs = kv_cache_specs(quantized=self.kv_quant == "int8",
                                latent=bool(self.cfg.kv_lora_rank))
        if self._spmd:
            # Multi-controller placement: shard_pytree's device_put only
            # works on fully-addressable inputs, so the tree is born sharded
            # — init runs as ONE GSPMD program with explicit out_shardings
            # (no process ever materializes the full tree), and checkpoints
            # stream per-process shards via make_array_from_callback.
            if params is None and _has_safetensors(weights_dir):
                params = self._load_checkpoint_global(
                    self.cfg, weights_dir, dtype, mesh, self._ns(pspecs),
                    quant=self.quant,
                )
            elif params is None:
                if self.quant == "int8":
                    from ..models.quant import init_llama_params_quantized

                    init_params = partial(
                        init_llama_params_quantized, self.cfg,
                        jax.random.PRNGKey(seed), scale_dtype=dtype,
                    )
                else:
                    init_params = partial(
                        init_llama_params, self.cfg, jax.random.PRNGKey(seed),
                        dtype=dtype,
                    )
                with mesh:
                    params = jax.jit(
                        init_params, out_shardings=self._ns(pspecs)
                    )()
            self.params = params
            with mesh:
                cache = jax.jit(
                    partial(init_kv_cache, self.cfg, max_slots, max_seq_len,
                            dtype=dtype, quantized=self.kv_quant == "int8"),
                    out_shardings=self._ns(cspecs),
                )()
        else:
            if params is None and _has_safetensors(weights_dir):
                # Real checkpoint: stream safetensors shards straight into
                # (sharded) HBM — already placed.
                params = load_llama_checkpoint(self.cfg, weights_dir, dtype=dtype, mesh=mesh)
            elif params is None:
                if self.quant == "int8":
                    # Direct int8 init: an 8B bf16 tree (16 GB) cannot be
                    # materialized-then-quantized inside one v5e chip's HBM.
                    from ..models.quant import init_llama_params_quantized

                    params = init_llama_params_quantized(
                        self.cfg, jax.random.PRNGKey(seed), scale_dtype=dtype
                    )
                else:
                    params = init_llama_params(self.cfg, jax.random.PRNGKey(seed), dtype=dtype)
            if self.quant == "int8":
                from ..models.quant import quantize_params

                params = quantize_params(params)  # no-op on already-int8 trees
            if (
                self.quant == "int8"
                and mesh is None
                and os.environ.get("LLM_MCP_TPU_FUSE_QKV", "1") != "0"
            ):
                # w8a8 layer-pass restructure: concat wq|wk|wv and w1|w3
                # post-quantization (bitwise-exact — models/quant.py:
                # fuse_layer_weights). Single-chip only: the fused output axis
                # interleaves head groups and cannot shard over tp.
                from ..models.quant import fuse_layer_weights

                params = fuse_layer_weights(params)
            if mesh is not None:
                params = shard_pytree(params, pspecs, mesh)
            self.params = params

            cache = init_kv_cache(
                self.cfg, max_slots, max_seq_len, dtype=dtype,
                quantized=self.kv_quant == "int8",
            )
            if mesh is not None:
                cache = shard_pytree(cache, cspecs, mesh)
        self._ck = cache["k"]
        self._cv = cache["v"]
        if self._spmd:
            # named out_sharding kinds for _shard_out: host-read outputs come
            # back fully replicated (every process device_gets locally — the
            # slice decode_fn convention), cache outputs keep their specs
            self._out_kinds = {
                "repl": self._repl_sharding,
                "k": self._ns(cspecs["k"]),
                "v": self._ns(cspecs["v"]),
            }

        # Host-side mirrors of per-slot device state. Invariant: only ACTIVE
        # (decoding) slots hold an in-range length; free/reserved slots park
        # at max_seq_len so the decode step's unconditional per-row K/V
        # scatter (models/llama.py w_idx) is out-of-bounds for them — JAX
        # drops OOB scatter writes, so parked rows are never touched. Without
        # this, decode rounds would write garbage rows inside a slot that is
        # mid-chunked-prefill (stale length 0) and corrupt its prompt KV.
        self._lengths = np.full(max_slots, max_seq_len, dtype=np.int32)
        self._last_tok = np.zeros(max_slots, dtype=np.int32)
        self._temp = np.zeros(max_slots, dtype=np.float32)
        self._topk = np.zeros(max_slots, dtype=np.int32)
        self._topp = np.ones(max_slots, dtype=np.float32)
        self._slots: list[_Slot | None] = [None] * max_slots
        self._prefills: dict[int, _PrefillState] = {}
        self._prefill_q: deque[int] = deque()

        self._rng_counter = 0
        self._base_key = jax.random.PRNGKey(seed + 1)

        # Sampling mask: model vocab may be padded beyond the tokenizer's
        # (MXU-friendly shapes) and control ids (pad/bos) must never be
        # sampled — only real text ids and eos are allowed.
        allowed = np.ones(self.cfg.vocab_size, dtype=bool)
        allowed[self.tokenizer.vocab_size :] = False
        for bad in (self.tokenizer.pad_id, self.tokenizer.bos_id):
            if bad != self.tokenizer.eos_id and 0 <= bad < self.cfg.vocab_size:
                allowed[bad] = False
        self._allowed_mask = jnp.asarray(allowed) if not allowed.all() else None

        self._decode_fn, self._fused_fn, self._fused_ragged_fn = (
            self._build_decode()
        )
        mask = self._allowed_mask
        cfg_ = self.cfg
        skey_base = self._base_key

        # the RNG key derives from the counter INSIDE the jit (fold_in of a
        # closed-over base key is a traced constant): an eagerly-folded key
        # would be a process-local device array, which cannot ride into a
        # GSPMD program beside global operands
        sample1 = jax.jit(
            lambda logits, counter, temp, topk, topp: sample_tokens(
                jnp.where(mask, logits, -jnp.inf) if mask is not None else logits,
                jax.random.fold_in(skey_base, counter), temp, topk, topp,
            ),
            **self._shard_out(["repl"]),
        )

        self._sample1 = sample1

        # constrained sibling of _sample1: same engine mask, then the
        # automaton mask + logit_bias, then EXACT sampling (approx top-k
        # could miss a tiny legal set entirely). Built lazily here but only
        # ever TRACED when a constrained batch reaches bsample — under
        # TPU_CONSTRAIN=0 no request carries cn, so this executable never
        # exists and the kill switch stays a zero-trace no-op.
        sample1_cn = jax.jit(
            lambda logits, counter, temp, topk, topp, masks, bids, bvals: sample_tokens(
                apply_token_mask(
                    jnp.where(mask, logits, -jnp.inf) if mask is not None else logits,
                    masks, bids, bvals,
                ),
                jax.random.fold_in(skey_base, counter), temp, topk, topp,
                exact=True,
            ),
            **self._shard_out(["repl"]),
        )

        self._sample1_cn = sample1_cn

        impl = self.attn_impl

        # Long-context path: with an sp axis in the mesh, prefill runs
        # sequence-parallel (ring attention over sp, Megatron TP over tp —
        # parallel/ring.py:llama_prefill_sp): per-chip activations are
        # [B, S/sp, D] and no full-sequence score matrix ever materializes,
        # so prompts whose attention would blow a single chip's HBM still
        # prefill. Decode is unchanged (its per-step work is tiny).
        # The sp kernel covers every dense family — windows/softcaps thread
        # into the ring masks, int8 weights dequant inside the shard_map —
        # so long context composes with quantization (the 8B int8 target).
        # MoE keeps the GSPMD prefill: experts ride the ep axis, not sp.
        # MLA keeps GSPMD too: the ring kernels are GQA-shaped (an MLA tree
        # has no wq/wk/wv) — its long-context prefill memory is bounded by
        # the query-blocked form instead (models/mla.py).
        self.sp = 1
        if mesh is not None and not cfg_.n_experts and not cfg_.kv_lora_rank:
            axes = dict(zip(mesh.axis_names, mesh.devices.shape))
            if (
                axes.get("sp", 1) > 1
                and axes.get("dp", 1) == 1  # engine prefills one prompt at a time
                and axes.get("pp", 1) == 1
                and axes.get("ep", 1) == 1
                and cfg_.n_kv_heads % axes.get("tp", 1) == 0
                and cfg_.vocab_size % axes.get("tp", 1) == 0
            ):
                self.sp = axes["sp"]

        # Ragged packed prefill (kernels/attention.py ragged_* family): the
        # chunked-prefill path of record when available. Fixed-shape packed
        # token buffer + per-row (slot, start, len) descriptors → zero pad
        # compute and ONE executable per (T, layout) instead of the bucketed
        # (Ab, bucket, skey) zoo. TPU_RAGGED_PREFILL=0 restores the bucketed
        # path bit-identically (the gate only selects the staging branch).
        # Gated to the same single-program regime as the prefix cache: no sp
        # ring, no mesh, and the model families the ragged kernels cover
        # (windows/softcaps stay bucketed).
        self.ragged_prefill = (
            os.environ.get("TPU_RAGGED_PREFILL", "1")
            not in ("", "0", "false", "no", "off")
            and self.prefill_chunk > 0
            and self.sp == 1
            and supports_ragged_prefill(mesh)
            and not cfg_.sliding_window
            and not cfg_.attn_softcap
        )
        # Sharded plane: the packed-buffer math is GSPMD-safe (tp shards the
        # head axis, pp the layer axis; neither touches the token packing),
        # but the pallas kernels themselves run on fully-addressable arrays
        # only — force the xla impl whenever the mesh spans devices.
        if mesh is not None and mesh.size > 1:
            self._ragged_impl = "xla" if self.ragged_prefill else ""
        else:
            self._ragged_impl = resolve_ragged_impl() if self.ragged_prefill else ""
        if self.ragged_prefill:
            hd = cfg_.resolved_head_dim
            cap = min(
                max(self.admit_batch * self.prefill_chunk, 1),
                ragged_prefill_max_tokens(
                    hd,
                    cfg_.n_kv_heads,
                    latent=cfg_.kv_lora_rank,
                    rope_dim=cfg_.qk_rope_head_dim if cfg_.kv_lora_rank else 0,
                ),
            )
            # pow2 floor: packed buffer lengths ride the pow2 ladder (the
            # kernel tiles T by block_q and asserts divisibility), so the cap
            # itself must sit on the ladder or a full group would bucket past
            # the VMEM budget ragged_prefill_max_tokens derived.
            self._ragged_cap = 1 << (cap.bit_length() - 1)
            log.info(
                "ragged prefill enabled: impl=%s cap=%d tokens",
                self._ragged_impl, self._ragged_cap,
            )
        else:
            self._ragged_cap = 0

        kv_q = self.kv_quant == "int8"
        # quantized GQA caches use the FUSED single-payload layout
        # (models/llama.py:init_kv_cache): cache["v"] is the empty dict and
        # V rides cache["k"]'s head axis. MLA int8 keeps its two-dict latent
        # layout; bf16 keeps bare arrays.
        fused_kv = kv_q and not self.cfg.kv_lora_rank
        dtype_ = dtype

        def _maybe_quant_kv(ks, vs):
            # quantize prompt KV INSIDE the prefill jit: the bf16 KV of a
            # batched admission (A × bucket rows × L layers) never
            # materializes in HBM outside the fused program
            if fused_kv:
                from ..models.llama import fuse_prompt_kv

                return fuse_prompt_kv(ks, vs, scale_dtype=dtype_), {}
            if kv_q:
                return (
                    quantize_kv(ks, scale_dtype=dtype_),
                    quantize_kv(vs, scale_dtype=dtype_),
                )
            return ks, vs

        self.pp_prefill = 1  # >1 when whole-prompt prefill rides the stage scan
        if self.sp > 1:
            from ..parallel.ring import llama_prefill_sp

            log.info("sequence-parallel prefill enabled: sp=%d", self.sp)

            def _prefill_body(params, tokens, lengths):
                logits, ks, vs = llama_prefill_sp(cfg_, params, tokens, lengths, mesh)
                ks, vs = _maybe_quant_kv(ks, vs)
                return logits, ks, vs

        else:
            # Pipeline-parallel prefill (parallel/pipeline.py): with a pp
            # axis in the mesh, whole-prompt admission runs the bit-parity
            # GPipe stage scan — layer-sharded params stay stage-local
            # instead of all-gathering per layer, so a model too big for one
            # slice's HBM serves across stages. Decode and chunked prefill
            # keep the generic GSPMD path (their per-call work is small and
            # correctness is sharding-independent). TPU_PP_PREFILL=0 falls
            # back to the single-stage scan (the parity reference).
            pp_ = 1
            if mesh is not None and not cfg_.n_experts and not cfg_.kv_lora_rank:
                pp_ = dict(zip(mesh.axis_names, mesh.devices.shape)).get("pp", 1)
            use_pp = (
                pp_ > 1
                and self.sp == 1
                and cfg_.n_layers % pp_ == 0
                and os.environ.get("TPU_PP_PREFILL", "1")
                not in ("", "0", "false", "no", "off")
            )
            self.pp_prefill = pp_ if use_pp else 1
            if use_pp:
                from ..parallel.pipeline import pipeline_prefill

                log.info("pipeline-parallel prefill enabled: pp=%d", pp_)

                def _prefill_body(params, tokens, lengths):
                    # microbatch count must divide B (pipeline_prefill
                    # asserts); B that doesn't split falls back to M=1
                    m = pp_ if tokens.shape[0] % pp_ == 0 else 1
                    logits, ks, vs = pipeline_prefill(
                        cfg_, params, tokens, lengths, mesh,
                        n_microbatches=m, attn_impl=impl,
                    )
                    ks, vs = _maybe_quant_kv(ks, vs)
                    return logits, ks, vs

            else:

                # jax.jit caches one executable per input shape, so prompt
                # buckets (power-of-two padded) each compile once without any
                # manual cache. quant_kv quantizes per layer INSIDE the
                # prefill scan: the stacked bf16 prompt KV of a batched
                # admission never materializes (llama_prefill docstring).
                def _prefill_body(params, tokens, lengths):
                    return llama_prefill(
                        cfg_, params, tokens, lengths, attn_impl=impl, quant_kv=kv_q
                    )

        def _insert_row(ck, cv, ks, vs, i, slot):
            # ks/vs: batched prompt KV [L, A, Hkv, bucket, hd] (already in
            # cache-entry form when the cache is quantized: fused
            # payload+scales for GQA, {"q","s"} per side for MLA) → write
            # row `i` at [:, slot, :, :bucket]. `i`/`slot` are traced
            # scalars; the dynamic_update_slice form updates the donated
            # cache in place (an advanced-index scatter would copy the full
            # cache payload).
            if fused_kv:
                ck = {
                    "q": jax.lax.dynamic_update_slice(
                        ck["q"], jax.lax.dynamic_slice_in_dim(ks["q"], i, 1, 1),
                        (0, slot, 0, 0, 0),
                    ),
                    "s": jax.lax.dynamic_update_slice(
                        ck["s"],
                        jax.lax.dynamic_slice_in_dim(ks["s"], i, 1, 1).astype(ck["s"].dtype),
                        (0, slot, 0, 0),
                    ),
                }
                return ck, cv
            if kv_q:
                ck = {
                    "q": jax.lax.dynamic_update_slice(
                        ck["q"], jax.lax.dynamic_slice_in_dim(ks["q"], i, 1, 1),
                        (0, slot, 0, 0, 0),
                    ),
                    "s": jax.lax.dynamic_update_slice(
                        ck["s"],
                        jax.lax.dynamic_slice_in_dim(ks["s"], i, 1, 1).astype(ck["s"].dtype),
                        (0, slot, 0, 0),
                    ),
                }
                cv = {
                    "q": jax.lax.dynamic_update_slice(
                        cv["q"], jax.lax.dynamic_slice_in_dim(vs["q"], i, 1, 1),
                        (0, slot, 0, 0, 0),
                    ),
                    "s": jax.lax.dynamic_update_slice(
                        cv["s"],
                        jax.lax.dynamic_slice_in_dim(vs["s"], i, 1, 1).astype(cv["s"].dtype),
                        (0, slot, 0, 0),
                    ),
                }
                return ck, cv
            kr = jax.lax.dynamic_slice_in_dim(ks, i, 1, 1)
            vr = jax.lax.dynamic_slice_in_dim(vs, i, 1, 1)
            ck = jax.lax.dynamic_update_slice(ck, kr.astype(ck.dtype), (0, slot, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, vr.astype(cv.dtype), (0, slot, 0, 0, 0))
            return ck, cv

        mask_ = self._allowed_mask
        base_key_ = self._base_key

        @partial(jax.jit, donate_argnums=(1, 2, 3, 4, 5, 6),
                 **self._shard_out(["k", "v", "repl", "repl", "repl", "repl",
                                   "repl"]))
        def admit_fn(params, ck, cv, d_temp, d_topk, d_topp, d_last, tokens,
                     ipack, fpack, cn=None):
            """Fused admission: prefill + cache insert + sampling-param
            update + first-token sample in ONE dispatch.

            The unfused form cost ~9+3A host<->device round trips per
            admission batch (separate transfers for every small array, a
            dispatch per cache-row insert, a sync for the sampled tokens) —
            on a remote-TPU tunnel each trip is tens of ms and admission
            dominated the serve loop (measured 56% of wall at 8B B=80).
            Fused: tokens + 2 packed arrays up, one dispatch, one [Ab]
            fetch.

            The sampled first tokens also land in `d_last` (the
            device-resident last-token ring the pipelined decode loop reads
            its round inputs from): the device stream is in-order, so any
            decode round dispatched after this admission sees tok0 without
            the host ever staging it.

            ipack i32 [3*Ab+2]: slots, prompt lengths, top_k, A (live row
            count), rng counter. fpack f32 [2*Ab]: temperature, top_p.
            """
            Ab = tokens.shape[0]
            slots = ipack[:Ab]
            lengths = ipack[Ab : 2 * Ab]
            topks = ipack[2 * Ab : 3 * Ab]
            live_n = ipack[3 * Ab]
            counter = ipack[3 * Ab + 1]
            temps = fpack[:Ab]
            topps = fpack[Ab:]

            logits, ks, vs = _prefill_body(params, tokens, lengths)

            def body(i, cc):
                ck, cv = cc
                # pad rows (i >= live_n) duplicate garbage prompts — they
                # must not write ANY cache row
                return jax.lax.cond(
                    i < live_n,
                    lambda cc: _insert_row(cc[0], cc[1], ks, vs, i, slots[i]),
                    lambda cc: cc,
                    (ck, cv),
                )

            ck, cv = jax.lax.fori_loop(0, Ab, body, (ck, cv))
            # sampling params live ON DEVICE between rounds (decode gathers
            # them by slot id — never re-transferred per round). Pad rows
            # scatter to row B: out of bounds, dropped (the same invariant
            # the KV parking relies on).
            row = jnp.where(jnp.arange(Ab) < live_n, slots, d_temp.shape[0])
            d_temp = d_temp.at[row].set(temps)
            d_topk = d_topk.at[row].set(topks)
            d_topp = d_topp.at[row].set(topps)
            if mask_ is not None:
                logits = jnp.where(mask_, logits, -jnp.inf)
            # constrained admission: automaton masks + logit_bias for the
            # FIRST sampled token. cn rides at the END defaulting to None
            # (the paged=None pattern) so unconstrained admissions keep the
            # exact executable traced before this subsystem existed.
            if cn is not None:
                logits = apply_token_mask(logits, cn[0], cn[1], cn[2])
            key = jax.random.fold_in(base_key_, counter)
            # pad rows duplicate garbage prompts/params — keep them out of
            # the sampler's homogeneity reductions (fast-path selection)
            toks0 = sample_tokens(
                logits, key, temps, topks, topps,
                active=jnp.arange(Ab) < live_n,
                exact=cn is not None,
            )
            d_last = d_last.at[row].set(toks0)
            return ck, cv, d_temp, d_topk, d_topp, d_last, toks0

        @partial(jax.jit, donate_argnums=(0, 1), **self._shard_out(["k", "v"]))
        def insert_cached_fn(ck, cv, pk, pv, slots, live_n):
            """Prefix-cache hit admission: write ONE cached prompt-prefix's
            KV rows into N slots in one dispatch. pk/pv: the stored rows
            [L, 1, Hkv, P0, hd] (int8 {"q","s"} pytree when the cache is).
            The suffix then prefills through the ordinary chunked path
            (start=P0) — reading these rows as its past; sampling params
            are set at activation as usual."""

            def body(i, cc):
                ck, cv = cc
                return jax.lax.cond(
                    i < live_n,
                    lambda cc: _insert_row(cc[0], cc[1], pk, pv, 0, slots[i]),
                    lambda cc: cc,
                    (ck, cv),
                )

            ck, cv = jax.lax.fori_loop(0, slots.shape[0], body, (ck, cv))
            return ck, cv

        @partial(jax.jit, donate_argnums=(0, 1), **self._shard_out(["k", "v"]))
        def insert_at_fn(ck, cv, pk, pv, slot, start):
            """Paged restore, private tail: write pk/pv [L, 1, Hkv, R, hd]
            (int8 {"q","s"} pytree when the cache is) into slot's rows
            [start, start+R). R is EXACT — never pow2-padded — because a
            padded R with start+R > S would make dynamic_update_slice CLAMP
            the start index backwards and overwrite the shared prefix rows
            just re-inserted below it. Restore guarantees start+R = bucket
            <= S, so the traced start is never clamped."""
            if fused_kv:
                ck = {
                    "q": jax.lax.dynamic_update_slice(
                        ck["q"], pk["q"], (0, slot, 0, start, 0)
                    ),
                    "s": jax.lax.dynamic_update_slice(
                        ck["s"], pk["s"].astype(ck["s"].dtype), (0, slot, 0, start)
                    ),
                }
                return ck, cv
            if kv_q:
                ck = {
                    "q": jax.lax.dynamic_update_slice(
                        ck["q"], pk["q"], (0, slot, 0, start, 0)
                    ),
                    "s": jax.lax.dynamic_update_slice(
                        ck["s"], pk["s"].astype(ck["s"].dtype), (0, slot, 0, start)
                    ),
                }
                cv = {
                    "q": jax.lax.dynamic_update_slice(
                        cv["q"], pv["q"], (0, slot, 0, start, 0)
                    ),
                    "s": jax.lax.dynamic_update_slice(
                        cv["s"], pv["s"].astype(cv["s"].dtype), (0, slot, 0, start)
                    ),
                }
                return ck, cv
            ck = jax.lax.dynamic_update_slice(
                ck, pk.astype(ck.dtype), (0, slot, 0, start, 0)
            )
            cv = jax.lax.dynamic_update_slice(
                cv, pv.astype(cv.dtype), (0, slot, 0, start, 0)
            )
            return ck, cv

        @partial(jax.jit, donate_argnums=(1, 2), static_argnames=("skey",),
                 **self._shard_out(["repl", "k", "v"]))
        def prefill_chunk_fn(params, ck, cv, tokens, slots, starts, nvalid, skey,
                             paged=None):
            # `paged` rides at the END so the donation indices above never
            # move; the pool is NOT donated (entries outlive every dispatch)
            return llama_prefill_chunk_batch(
                cfg_, params, ck, cv, tokens, slots, starts, nvalid, skey=skey,
                paged=paged,
            )

        @partial(jax.jit, donate_argnums=(1, 2), static_argnames=("skey",),
                 **self._shard_out(["repl", "k", "v"]))
        def ragged_chunk_fn(params, ck, cv, tokens, rowids, positions, slots,
                            starts, last_idx, skey, paged=None):
            # standalone ragged dispatch (pure-prefill window); same trailing-
            # `paged` / donation contract as prefill_chunk_fn
            return llama_prefill_chunk_ragged(
                cfg_, params, ck, cv, tokens, rowids, positions, slots,
                starts, last_idx, skey=skey, paged=paged,
            )

        self._admit_fn = admit_fn
        self._insert_cached_fn = insert_cached_fn
        self._insert_at_fn = insert_at_fn
        self._prefill_chunk_fn = prefill_chunk_fn
        self._ragged_chunk_fn = ragged_chunk_fn
        # Prompt-prefix KV cache (vLLM-style prefix reuse, exact-prefix
        # match): production chat traffic repeats long shared prefixes
        # (system prompts, few-shot preambles) across requests; their KV is
        # a pure function of the weights, so re-prefilling them per request
        # is pure waste. Entries store device-resident KV rows for a prompt
        # PREFIX; a hit copies the rows into the slot (one fused dispatch
        # per hit group) and only the suffix runs through chunked prefill.
        # LRU by bytes; 0 disables. Gated to chunked prefill + sp == 1
        # (the sp path prefills whole prompts by design).
        self._prefix_cache: "OrderedDict[tuple, dict]" = OrderedDict()
        # secondary index: stored-prefix length → {key: entry}. Stored
        # lengths are pow2-floored (_maybe_store_prefix), so a lookup is
        # O(log max_seq_len) dict probes instead of a linear scan comparing
        # every entry's full key (_match_prefix). Kept exactly in sync with
        # _prefix_cache at the insert and evict sites.
        self._prefix_by_len: dict[int, dict[tuple, dict]] = {}
        self._prefix_cache_bytes = 0
        # Gated to chunked prefill + sp == 1 only (the sp path prefills
        # whole prompts by design). The old single-chip gate is LIFTED:
        # entries are eager slices of the (possibly sharded) global cache,
        # and every entry mutation flows through the dispatch plane, so the
        # prefix tier runs identically on local arrays, a local mesh, and
        # the GSPMD leader/follower plane.
        self._prefix_budget = (
            int(prompt_cache_mb) * (1 << 20)
            if self.prefill_chunk > 0 and self.sp == 1
            else 0
        )
        self._recent_prompts: deque[tuple] = deque(maxlen=16)
        self.prefix_cache_hits = 0
        self.prefix_cache_misses = 0
        # Fleet prefix tier (routing/prefix.py): _prefix_pub mirrors the
        # resident chain set {key: stored_tokens} behind its own lock so
        # digest building (discovery refresh thread) and match probes
        # (serve threads) never touch the engine-thread-owned OrderedDict.
        # prefix_export/prefix_import park work on _prefix_rpc_in; the
        # engine thread services it in _admit_pending, where touching
        # _prefix_cache and dispatching pool uploads is safe.
        self._prefix_pub: dict[tuple, int] = {}
        self._prefix_pub_lock = threading.Lock()
        self._prefix_rpc_in: "queue.Queue[tuple]" = queue.Queue()
        self.prefix_exports_total = 0
        self.prefix_export_bytes_total = 0
        self.prefix_imports_total = 0
        self.prefix_import_bytes_total = 0
        self.prefix_import_rejects_total = 0
        # device-resident sampling params (see admit_fn docstring); host
        # mirrors (self._temp/_topk/_topp) stay the source of truth for
        # rebuild after a poisoned dispatch consumed the donated buffers.
        # Under GSPMD these are born replicated globals (jnp.asarray would
        # make process-local arrays no jit may mix with global operands).
        _up = self._put_repl if self._spmd else jnp.asarray
        self._d_temp = _up(self._temp)
        self._d_topk = _up(self._topk)
        self._d_topp = _up(self._topp)
        # device-resident last-token ring: decode rounds read their input
        # tokens from it and write their final tokens back, admissions write
        # first samples — so dispatching round N+1 never waits for round N's
        # fetch (decode_chunk_fn docstring). Host mirror: self._last_tok
        # (updated at fetch, for recovery after a poisoned dispatch).
        self._d_last_tok = _up(self._last_tok)
        # Pipeline depth: how many decode rounds may be in flight before the
        # oldest is fetched. Depth d hides a tunnel round-trip of up to
        # (d-1) x round-compute behind the device chain (a remote-TPU
        # tunnel's RTT was measured swinging 0.1-1.2 s between runs — at
        # depth 1 every swing lands directly on tok/s). The cost: a slot
        # that finishes decodes up to d-1 extra discarded rounds before the
        # host sees the finish, and freed slots cool for the in-flight
        # rounds that still reference them (_free_slot). Default: 2 on an
        # accelerator, 1 on CPU (no tunnel to hide; sequential-generate
        # tests would only pay the finished-slot waste).
        depth_env = os.environ.get("TPU_PIPELINE_DEPTH", "")
        if depth_env:
            self.pipeline_depth = max(1, int(depth_env))
        else:
            try:
                on_accel = jax.default_backend() != "cpu"
            except Exception:  # pragma: no cover
                on_accel = False
            self.pipeline_depth = 2 if on_accel else 1
        # round ids: fence for slot-reuse cooling (a freed slot may still be
        # referenced by rounds dispatched before the free was observed)
        self._rid_dispatched = 0
        self._rid_fetched = 0
        self._cooling: dict[int, int] = {}

        # Self-speculative decoding (draft-and-verify): a host-side n-gram
        # drafter (drafter.py — prompt-lookup over each slot's own history)
        # proposes up to TPU_SPEC_K tokens; one chunk-machinery model call
        # verifies them all (_build_verify), accepting the longest agreeing
        # prefix — exact greedy equality at temp=0, rejection sampling
        # otherwise (ops/sampling.py:spec_verify). Rejected positions roll
        # back by arithmetic alone: the cache rows past the accepted
        # position are dead under the parked-slot OOB invariant (chunk reads
        # mask key_pos < starts, decode attends < length, later writes
        # overwrite in place). TPU_SPEC=0 is a hard kill switch: none of
        # the spec code runs and the decode path is byte-identical. Gated
        # to sp == 1 (the sp prefill path never chunks; verify rides the
        # chunk machinery).
        self.spec_k = max(0, int(os.environ.get("TPU_SPEC_K", "") or 7))
        self.spec_min_ngram = max(
            1, int(os.environ.get("TPU_SPEC_MIN_NGRAM", "") or 2)
        )
        self.spec_max_ngram = max(self.spec_min_ngram, 3)
        self.spec_enabled = (
            os.environ.get("TPU_SPEC", "1") != "0"
            and self.spec_k > 0
            and self.sp == 1
        )
        # verify-round throughput counters (speculation_stats; engine-thread
        # writers, lock-free like total_tokens)
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        self.spec_calls = 0
        # adaptive throttle: drafts that keep getting rejected make a verify
        # round strictly worse than a decode round (1 emitted token per slot
        # vs decode_chunk) — back off for a while after a low-acceptance call
        self._spec_cooldown = 0
        self._verify_fn = self._build_verify() if self.spec_enabled else None

        # Grammar-constrained decoding (constrain/): schema/regex/choice
        # specs compile to byte automata lifted to packed token bitmasks,
        # one SlotAutomaton cursor per constrained slot, masks fused into
        # sampling (admit / cnstep / bsample / verify). TPU_CONSTRAIN=0 is
        # a hard kill switch mirroring TPU_SPEC=0: the compiler is never
        # constructed, no request ever carries `cn`, every jitted path
        # keeps its cn=None trailing operand — zero new executables traced
        # and token-identical greedy output.
        self.constrain_enabled = constrain.constrain_enabled()
        self.cn_bias_max = max(
            1, int(os.environ.get("LLM_MCP_TPU_CN_BIAS_MAX", "") or 64)
        )
        self._constrain = (
            constrain.ConstraintCompiler(
                self.tokenizer, self.cfg.vocab_size,
                cache_size=int(os.environ.get("TPU_CONSTRAIN_CACHE", "") or 64),
            )
            if self.constrain_enabled
            else None
        )
        # constrained-traffic counters (constrain_stats; engine-thread
        # writers, lock-free like the spec counters)
        self.cn_requests = 0
        self.cn_tokens = 0
        self.cn_illegal = 0  # automaton-illegal emissions — must stay 0
        self.cn_finished = 0
        self.cn_finished_accepting = 0
        self.cn_spec_drafted = 0
        self.cn_spec_accepted = 0
        self.cn_mask_s = 0.0  # host wall building/gathering mask rows
        # masked single-step decode for constrained slots (built lazily on
        # first constrained traffic — never traced otherwise)
        self._cn_step_fn = None

        # HBM-aware KV pool (memory.py): admission watermark + slot
        # preemption with host offload. TPU_KV_HOST_OFFLOAD=0 (default)
        # never constructs the pool — every hot-path touch point is guarded
        # `if self._pool is not None`, so the off state is a true no-op
        # (byte-identical scheduler decisions vs the pool-less engine).
        self._pool = None
        if os.environ.get("TPU_KV_HOST_OFFLOAD", "0") not in ("", "0", "false", "no", "off"):
            self._pool = KVPool(
                max_slots=max_slots,
                max_seq_len=max_seq_len,
                bytes_per_slot=pytree_nbytes({"k": self._ck, "v": self._cv})
                // max(1, max_slots),
                watermark=float(os.environ.get("TPU_ADMIT_WATERMARK", "") or 1.5),
                policy=os.environ.get("TPU_PREEMPT_POLICY", "") or "priority",
            )
            log.info(
                "KV pool enabled: %.1f MB/slot, watermark %.2f, policy %s",
                self._pool.bytes_per_slot / (1 << 20),
                self._pool.watermark,
                self._pool.policy,
            )

        # Paged KV ledger (paging.py): refcounted block tables + COW prefix
        # sharing over the slot arena. Pure host bookkeeping (no device
        # calls), so it is ALWAYS constructed — the block economy feeds
        # telemetry unconditionally, and when the pool is on, admission's
        # offered load becomes unique-block accounting (_offered_load).
        cache_bytes = pytree_nbytes({"k": self._ck, "v": self._cv})
        self._paging = PagedKVManager(
            max_slots=max_slots,
            max_seq_len=max_seq_len,
            bytes_per_token=cache_bytes // max(1, max_slots * max_seq_len),
            prefix_budget_bytes=self._prefix_budget,
        )
        self._snap_ctr = 0  # KVSnapshot ids for the paging ledger's parked pins
        log.info(
            "paged KV: %d-token blocks, %d/slot, %d arena + %d prefix blocks",
            self._paging.block_tokens, self._paging.blocks_per_slot,
            self._paging.slot_partition, self._paging.prefix_partition,
        )

        # Physical half of the paged ledger (physical.py): per-slot device
        # block tables + a prefix block pool, so prefix-hit admission is
        # PIN-ONLY (zero row copies — sharers read the one pool copy through
        # the table) instead of duplicating entry rows into every slot.
        # TPU_PAGED_PHYSICAL=0 is a true escape hatch: no tables, no pool,
        # every dispatch takes the exact pre-physical trace. Gated to the
        # same chunked-prefill world as the prefix cache itself
        # (_prefix_budget > 0 implies all of that), plus block sizes the
        # attention kernels' paged arms accept.
        self._phys: PhysicalPool | None = None
        self._pool_k = self._pool_v = None
        self._cow_fn = _cow_block_fn
        self._pool_arena_fn = _pool_put_arena_fn
        self._pool_pool_fn = _pool_put_pool_fn
        self._pool_host_fn = _pool_put_host_fn
        bt_ = self._paging.block_tokens
        if (
            os.environ.get("TPU_PAGED_PHYSICAL", "1")
            not in ("", "0", "false", "no", "off")
            and self._prefix_budget > 0
            and self._paging.prefix_partition >= 1
            and max_seq_len % bt_ == 0
            and bt_ in (32, 64, 128, 256)
        ):
            self._phys = PhysicalPool(
                n_slots=max_slots, seq_len=max_seq_len, block_tokens=bt_,
                pool_rows=self._paging.prefix_partition,
            )
            # honest HBM accounting peak (bench.py paged_hbm_bytes_ratio):
            # contiguous-equivalent bytes ÷ physically-resident bytes,
            # sampled at every shared admission (the sharing peak)
            self._phys_hbm_peak_ratio = 1.0
            self._phys_hbm_peak = (0.0, 0.0)
            if self._spmd:
                # born sharded (the multi-controller placement rule): build
                # the pool shapes host-side, then allocate as one GSPMD
                # program — pool_like's eager zeros would be process-local
                specs = kv_pool_specs(
                    quantized=self.kv_quant == "int8",
                    latent=bool(self.cfg.kv_lora_rank),
                )
                rows_ = self._paging.prefix_partition

                def _pool_shapes(cache):
                    return jax.tree.map(
                        lambda c: jax.ShapeDtypeStruct(
                            (c.shape[0], rows_, c.shape[2], bt_) + c.shape[4:],
                            c.dtype,
                        ),
                        cache,
                    )

                def _alloc(shapes):
                    return jax.tree.map(
                        lambda s: jnp.zeros(s.shape, s.dtype), shapes
                    )

                with self.mesh:
                    self._pool_k = jax.jit(
                        partial(_alloc, _pool_shapes(self._ck)),
                        out_shardings=self._ns(specs["k"]),
                    )()
                    self._pool_v = jax.jit(
                        partial(_alloc, _pool_shapes(self._cv)),
                        out_shardings=self._ns(specs["v"]),
                    )()
                self._out_kinds["pk"] = self._ns(specs["k"])
                self._out_kinds["pv"] = self._ns(specs["v"])
                self._cow_fn = jax.jit(
                    _cow_block_raw, donate_argnums=(0, 1),
                    **self._shard_out(["k", "v"]),
                )
                self._pool_arena_fn = jax.jit(
                    _pool_put_arena_raw, donate_argnums=(0, 1),
                    **self._shard_out(["pk", "pv"]),
                )
                self._pool_pool_fn = jax.jit(
                    _pool_put_pool_raw, donate_argnums=(0, 1),
                    **self._shard_out(["pk", "pv"]),
                )
                self._pool_host_fn = jax.jit(
                    _pool_put_host_raw, donate_argnums=(0, 1),
                    **self._shard_out(["pk", "pv"]),
                )
            else:
                self._pool_k = pool_like(self._ck, self._paging.prefix_partition, bt_)
                self._pool_v = pool_like(self._cv, self._paging.prefix_partition, bt_)
                if self.mesh is not None:
                    # size-1 meshes pass the gate; keep the pool's placement
                    # commitment consistent with the arena's (pool-row axis
                    # replicates — rows are a global resource, not dp-sliced)
                    specs = kv_pool_specs(
                        quantized=self.kv_quant == "int8",
                        latent=bool(self.cfg.kv_lora_rank),
                    )
                    self._pool_k = shard_pytree(self._pool_k, specs["k"], self.mesh)
                    self._pool_v = shard_pytree(self._pool_v, specs["v"], self.mesh)
            log.info(
                "physical paged KV: [%d, %d] block table + %d-row prefix pool"
                " (%.1f MB)",
                max_slots, self._phys.nbs, self._phys.pool_rows,
                pytree_nbytes({"k": self._pool_k, "v": self._pool_v}) / (1 << 20),
            )

        # KV migration (migration.py): engine-to-engine snapshot transfer.
        # TPU_MIGRATE=0 (default) keeps both queues None — every hot-path
        # touch point is guarded `is not None`, so the off state is a true
        # no-op exactly like the pool's. The outbox carries wire payloads a
        # MigrationCoordinator ships out; the inbox carries decoded
        # snapshots the run loop restores into free slots.
        self._migrate_outbox: "queue.Queue[dict] | None" = None
        self._migrate_in: "queue.Queue[tuple] | None" = None
        # engine-level prefill-role flag: a coordinator sets it (or tests
        # do) so every admitted request exports after its prefill lands;
        # per-request GenRequest.migrate_after_prefill overrides ad hoc
        self.migrate_after_prefill = False
        self.migrated_out_total = 0
        self.migrated_in_total = 0
        self.migrate_out_bytes_total = 0
        self.migrate_in_bytes_total = 0
        if os.environ.get("TPU_MIGRATE", "0") not in ("", "0", "false", "no", "off"):
            self._migrate_outbox = queue.Queue()
            self._migrate_in = queue.Queue()
            log.info("KV migration enabled (TPU_MIGRATE)")

        self._admit: "queue.Queue[GenRequest]" = queue.Queue()
        self._stop_evt = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None

        # Flight recorder + anomaly detectors + compile ledger
        # (telemetry/recorder.py, TPU_FLIGHT knobs; doc/observability.md).
        # The recorder/ledger are process-shared (like the tracer) so all
        # engines land events in one ring; the anomaly monitor is per-engine
        # because its detectors watch THIS engine's cadence/TTFT/leaks.
        self._flight = flight.get_recorder()
        self._ledger = flight.get_compile_ledger()
        self._anomaly = flight.AnomalyMonitor(
            self._flight, target_ttft_ms=self.target_ttft_ms
        )
        # Perf observatory (telemetry/perf.py): ITL/TPOT timelines, goodput
        # accounting, and sampled steady-state phase attribution with
        # roofline MFU/MBU — the CompileLedger's steady-state complement.
        # Per-engine (its roofline is this engine's model shape); stdlib
        # module, so the engine hands it plain scalars only.
        self._perf = perf.PerfObservatory(
            shape=perf.ModelShape.from_config(self.cfg),
            active_layout=perf.layout_name(
                bool(self.cfg.kv_lora_rank), self.kv_quant == "int8"
            ),
            paged=self._phys is not None,
            block_tokens=self._paging.block_tokens,
            weight_bytes_per_param=(
                1.0 if self.quant == "int8" else jnp.dtype(dtype).itemsize
            ),
            target_ttft_ms=self.target_ttft_ms,
        )
        # Workload capture + latency waterfall (telemetry/workload.py).
        # The capture ring is process-shared (like the flight recorder) so
        # a fleet of engines streams one trace; the waterfall is per-engine
        # — its stage windows describe THIS engine's scheduling. Both are
        # stdlib modules; the engine hands them plain scalars/lists only.
        self._workload = workload.get_workload()
        self._waterfall = workload.LatencyWaterfall()
        # wall of the previous round completion: the sampled "wait" bucket
        # (scheduler/host gap between consecutive device rounds)
        self._perf_mark = time.perf_counter()
        # watchdog/compile-grace state transition counts (satellite of the
        # shed-while-compiling post-mortem gap): bridged to
        # llmtpu_watchdog_transitions_total{state=...} by engines_info
        self.watchdog_transitions: dict[str, int] = {}
        self._last_round_ts = time.time()  # decode-cadence stall signal
        # On-demand jax.profiler capture (/v1/debug/profile, or auto-armed
        # for the next N loop steps after an anomaly dump when
        # TPU_FLIGHT_PROFILE_STEPS > 0). All state transitions happen on
        # the engine thread; other threads only set the pending request.
        self._profile_pending: tuple[int, str] | None = None
        self._profile_left = 0
        self._profile_dir = ""
        _psteps = int(os.environ.get("TPU_FLIGHT_PROFILE_STEPS", "0") or 0)
        if _psteps > 0:
            self._flight.add_dump_callback(
                lambda info, n=_psteps: self.start_profile(n)
            )
        # paged ledger tap: COW / pin / unpin / snapshot ops become flight
        # events (the callback runs under the paging lock — the recorder's
        # lock-free append is the only thing it may do)
        self._paging.on_ops = self._paging_event

        # Stall watchdog: a wedged accelerator link (observed in the field:
        # the remote-TPU tunnel's session lock held by a dead client — even
        # jax.devices() blocks forever) leaves the engine thread stuck in a
        # device call it can never be interrupted out of. The loop stamps
        # progress each iteration; when in-flight work exists and the stamp
        # goes stale past TPU_STALL_TIMEOUT_S (default 600 s — first 8B
        # compiles legitimately take minutes), the watchdog sheds load:
        # new submits are rejected, queued-but-unadmitted requests get
        # error events (their consumers would otherwise hang), and
        # stall_seconds() lets the serving layer flip the device offline
        # so routing fails over (the reference's analog maps connection
        # errors to device-offline: worker/llm_worker/main.py:189-196 —
        # a wedged XLA runtime produces no error to map, only silence).
        self.last_progress = time.time()
        self.stall_timeout_s = float(
            os.environ.get("TPU_STALL_TIMEOUT_S", "600") or 0
        )
        self.stalled = False
        # First-time executable shapes (a new compact-decode bucket, a new
        # chunked-prefill (bucket, skey), a new admit bucket) legitimately
        # compile — minutes on a cold cache over a slow link. Dispatching a
        # never-seen shape extends a grace window so the watchdog doesn't
        # shed a healthy engine mid-compile; the cost is that a real wedge
        # during that window is detected one timeout later.
        self._seen_exec_shapes: set[tuple] = set()
        self._compile_grace_until = 0.0
        # Warmup planner (executor/warmup.py): built by start_warmup() at
        # boot (serving entrypoints / bench coldstart), None on the plain
        # test path and under TPU_WARMUP=0 — readiness then reads as
        # fully_warm (an unwarmed engine is not "warming", it is simply
        # pre-warmup-era cold, and must route exactly as before).
        self._warmup = None
        if self.stall_timeout_s > 0:
            threading.Thread(
                target=self._watchdog, name="engine-watchdog", daemon=True
            ).start()

        # rolling stats for dashboard/benchmarks. Rank 10 (doc/concurrency.md):
        # lowest rank, so holding it permits taking the pool/paging locks but
        # never the reverse — today no engine path nests it with either.
        self.stats_lock = OrderedLock("engine.stats", rank=10)
        self.total_tokens = 0
        self.total_requests = 0
        # requests failed with an error event (poisoned rounds, failed
        # prefills, cache loss) — bench.py refuses a serve window where this
        # moved (a degenerate run must never become the metric of record)
        self.total_errors = 0
        # cleanly finished requests + their completion tokens: the ratio is
        # the mean completion length, bench.py's decode-actually-ran guard
        self.finished_requests = 0
        self.finished_tokens = 0
        # rolling client-observed TTFT samples (ts, ttft_ms): the planner
        # records p50/p95 into `benchmarks` so routing's latency constraint
        # sees REAL serve percentiles (reference analog: probe scripts
        # writing p50/p95 rows, scripts/probe_openrouter_models.py:113-124)
        self._ttft_window: deque[tuple[float, float]] = deque(maxlen=1024)
        self._window: list[tuple[float, int]] = []  # (ts, tokens) for tps
        # engine-loop wall-clock by phase (serve budget breakdown): decode
        # dispatch staging, round fetch-wait, admission, chunked prefill,
        # token emission, idle. bench.py snapshots this across the serve
        # window so the serve↔raw gap has named components.
        self._phase_s: dict[str, float] = {
            k: 0.0 for k in ("dispatch", "fetch", "admit", "prefill", "emit", "idle")
        }

        # Dispatch-plane device state owned by the op closures (replicated
        # by construction on followers, because only ops mutate it):
        # per-group prefill logits parked between the chunk dispatch and the
        # activation sample, keyed by the leader-assigned group id riding
        # the payload; prefix-entry device rows keyed by entry id.
        self._x_logits: dict[int, Any] = {}
        self._x_prefix: dict[int, tuple] = {}
        self._gid_ctr = 0
        self._eid_ctr = 0
        self._ops = self._build_ops()

    # -- dispatch plane ----------------------------------------------------

    def _ns(self, specs):
        """PartitionSpec tree → NamedSharding tree on this engine's mesh."""
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )

    def _shard_out(self, kinds: list[str]) -> dict:
        """out_shardings kwargs for a jit definition: empty on the local
        plane (XLA chooses), explicit under GSPMD so host-read outputs come
        back fully replicated (every process device_gets its copy locally —
        no separate collective) and cache/pool outputs keep their specs.
        kinds name _out_kinds entries positionally: "repl", "k", "v",
        "pk", "pv"."""
        if not self._spmd:
            return {}
        outs = tuple(self._out_kinds[k] for k in kinds)
        return {"out_shardings": outs if len(outs) > 1 else outs[0]}

    def _fetch(self, tree):
        """Device→host fetch that is legal on every plane: local arrays
        device_get directly; under GSPMD a sharded global is resharded to
        fully-replicated first (device_get only addresses local shards)."""
        if self._spmd:
            tree = jax.tree.map(self._put_repl, tree)
        return jax.device_get(tree)

    @staticmethod
    def _load_checkpoint_global(cfg, ckpt_dir, dtype, mesh, shardings, quant: str = ""):
        """Every process reads the safetensors dir (standard multi-host
        practice) and contributes ONLY its addressable shards via
        make_array_from_callback — the full tree is never resident per
        process beyond the mmap'd host file."""
        from contextlib import nullcontext

        from ..models.weights import hf_to_llama_params, read_checkpoint_dir

        host = hf_to_llama_params(cfg, read_checkpoint_dir(ckpt_dir))
        if quant == "int8":
            from ..models.quant import quantize_params

            # quantize the host tree BEFORE placement so its structure matches
            # the quantized PartitionSpecs; pin the work to the CPU backend —
            # the tree must stay host-resident until make_array_from_callback
            # streams per-process shards
            try:
                cpu = jax.local_devices(backend="cpu")[0]
            except RuntimeError:
                cpu = None
            with jax.default_device(cpu) if cpu is not None else nullcontext():
                host = quantize_params(host)
        elif quant:
            raise NotImplementedError(
                f"engine quant={quant!r} with a checkpoint (only 'int8' is supported)"
            )

        def up(arr, sharding):
            a = np.asarray(arr)
            # int8 payloads must keep their dtype; only float leaves
            # (weights, scales, norms) follow the engine compute dtype
            if dtype is not None and np.issubdtype(a.dtype, np.floating):
                a = a.astype(dtype)
            return jax.make_array_from_callback(
                a.shape, sharding, lambda idx: a[idx]
            )

        return jax.tree.map(up, host, shardings)

    def _dx(self, op: str, *args):
        """THE dispatch funnel: every device mutation the scheduling loop
        makes goes through here — the backend sees the serialized (op,
        payload) step first (followers will replay the same closure from
        the same payload), then the op executes locally. Payloads are
        host-only values (numpy/int/str/bytes trees); device state lives on
        `self` and is read/written by the op closures alone. A step that
        RAISES under GSPMD kills the engine: the frame already fanned out,
        so followers executed (or wedged on) the same op and no local
        recovery can put every process back in the same state."""
        self._backend.emit(op, args)
        try:
            return self._ops[op](*args)
        except Exception as e:
            if self._spmd:
                self._mark_dead(f"dispatch {op!r} failed: {e}")
            raise

    def run_follower(self) -> None:
        """Blocking step-program replay loop for non-leader processes of a
        GSPMD backend: every received (op, payload) step executes the SAME
        op closure the leader ran, so device state stays replicated.
        Returns on the leader's stop command."""
        self._backend.run_follower(self._ops)

    def _paged_payload(self):
        """Host-side paged-dispatch descriptor riding the op payload: the
        numpy block table (policy state followers don't have), or None when
        the physical pool is off."""
        return self._phys.table if self._phys is not None else None

    def _paged_from(self, tbl):
        """Rebuild a jit `paged` argument from an op payload. Local plane:
        use the cached device table (one upload per mutation, not per
        dispatch). GSPMD: the numpy table enters the jit directly as a
        replicated operand."""
        if tbl is None:
            return None
        dev = tbl if self._spmd else self._phys.device_table()
        return {"tbl": dev, "k": self._pool_k, "v": self._pool_v}

    def _mark_dead(self, msg: str) -> None:
        """Poisoned dispatch under a GSPMD backend: the step already went
        out to followers and device state cannot be rebuilt replayably —
        the engine goes dead (submits reject, the loop exits, followers get
        the stop command from the loop tail)."""
        with self._dead_lock:
            if not self.dead:
                self.dead = msg or "dispatch failed"
        self._stop_evt.set()
        self._wake.set()

    def _build_ops(self) -> dict:
        """The step-program vocabulary: op name → closure holding ALL the
        device work of that step. Closures take host-only payloads, read
        and write device state through `self`, and are the ONLY code that
        touches jits/eager device ops after __init__ — the dispatch-surface
        lint pass reconciles this registry against dispatch.DISPATCH_OPS
        and the engine's _dx call sites both ways."""
        ops: dict[str, Any] = {}

        def op_admit(tokens, ipack, fpack, cn=None):
            # jits read via self._admit_fn at call time (tests monkeypatch it)
            (self._ck, self._cv, self._d_temp, self._d_topk, self._d_topp,
             self._d_last_tok, toks0) = self._admit_fn(
                self.params, self._ck, self._cv, self._d_temp, self._d_topk,
                self._d_topp, self._d_last_tok, tokens, ipack, fpack, cn=cn,
            )
            return toks0

        ops["admit"] = op_admit

        def op_insert(eid, slots, live_n):
            pk, pv = self._x_prefix[eid]
            self._ck, self._cv = self._insert_cached_fn(
                self._ck, self._cv, pk, pv, slots, np.int32(live_n)
            )

        ops["insert"] = op_insert

        def op_insrows(hk, hv, slots, live_n):
            # host KV rows ride the payload (restore / migrate-in: the
            # follower never saw this KV) and enter the jit as replicated
            # numpy operands
            self._ck, self._cv = self._insert_cached_fn(
                self._ck, self._cv, hk, hv, slots, np.int32(live_n)
            )

        ops["insrows"] = op_insrows

        def op_insat(hk, hv, slot, start):
            self._ck, self._cv = self._insert_at_fn(
                self._ck, self._cv, hk, hv, np.int32(slot), np.int32(start)
            )

        ops["insat"] = op_insat

        def op_chunk(gid, tokens, slots, starts, nvalid, skey, tbl):
            logits, self._ck, self._cv = self._prefill_chunk_fn(
                self.params, self._ck, self._cv, tokens, slots, starts,
                nvalid, skey=skey, paged=self._paged_from(tbl),
            )
            self._x_logits[gid] = logits

        ops["chunk"] = op_chunk

        def op_ragged(gid, tokens, rowids, positions, slots, starts,
                      last_idx, skey, tbl):
            logits, self._ck, self._cv = self._ragged_chunk_fn(
                self.params, self._ck, self._cv, tokens, rowids, positions,
                slots, starts, last_idx, skey=skey, paged=self._paged_from(tbl),
            )
            jax.block_until_ready(self._ck)
            self._x_logits[gid] = logits

        ops["ragged"] = op_ragged

        def op_bsample(gid, rows, slots_fin, temps, topks, topps, counter,
                       cn=None):
            # activation sample off a parked chunk group's boundary logits +
            # the sampling-param/token-ring writes for the finishing slots
            logits = self._x_logits.pop(gid, None)
            if logits is None or len(rows) == 0:
                return None
            if cn is not None:
                # constrained activation (chunked-prefill and prefix-hit
                # admissions): the masked sibling jit — only ever traced
                # when constrained traffic reaches this path
                toks0 = self._sample1_cn(
                    logits[rows], np.int32(counter), temps, topks, topps,
                    cn[0], cn[1], cn[2],
                )
            else:
                toks0 = self._sample1(
                    logits[rows], np.int32(counter), temps, topks, topps
                )
            self._d_temp = self._d_temp.at[slots_fin].set(temps)
            self._d_topk = self._d_topk.at[slots_fin].set(topks)
            self._d_topp = self._d_topp.at[slots_fin].set(topps)
            self._d_last_tok = self._d_last_tok.at[slots_fin].set(toks0)
            return toks0

        ops["bsample"] = op_bsample

        def op_decode(kind, gid, packed, p_args, compact, skey, tbl):
            paged = self._paged_from(tbl)
            if kind == "plain":
                out, self._ck, self._cv, self._d_last_tok = self._decode_fn(
                    self.params, self._ck, self._cv, packed, self._d_temp,
                    self._d_topk, self._d_topp, self._d_last_tok,
                    compact=compact, paged=paged,
                )
                return out
            fn = self._fused_fn if kind == "fused" else self._fused_ragged_fn
            out, logits, self._ck, self._cv, self._d_last_tok = fn(
                self.params, self._ck, self._cv, packed, self._d_temp,
                self._d_topk, self._d_topp, self._d_last_tok, *p_args,
                compact=compact, skey=skey, paged=paged,
            )
            self._x_logits[gid] = logits
            return out

        ops["decode"] = op_decode

        def op_verify(tokens, slots, starts, nvalid, drafts, ndraft,
                      counter, skey, tbl, cn=None):
            (n_acc, final, self._ck, self._cv,
             self._d_last_tok) = self._verify_fn(
                self.params, self._ck, self._cv, self._d_last_tok,
                self._d_temp, self._d_topk, self._d_topp, tokens, slots,
                starts, nvalid, drafts, ndraft, np.int32(counter),
                skey=skey, paged=self._paged_from(tbl), cn=cn,
            )
            return n_acc, final

        ops["verify"] = op_verify

        def op_cnstep(packed, masks, bids, bvals, tbl):
            # masked single-step decode for constrained slots. The jit is
            # built on first use — leader and follower alike only ever
            # trace it when constrained traffic actually dispatches here,
            # which is what keeps TPU_CONSTRAIN=0 a zero-trace no-op.
            if self._cn_step_fn is None:
                self._cn_step_fn = self._build_cn_step()
            out, self._ck, self._cv, self._d_last_tok = self._cn_step_fn(
                self.params, self._ck, self._cv, packed, self._d_temp,
                self._d_topk, self._d_topp, self._d_last_tok, masks, bids,
                bvals, paged=self._paged_from(tbl),
            )
            return out

        ops["cnstep"] = op_cnstep

        def op_samprow(b, temp, topk, topp, last):
            # single-slot sampling-state restore (preempt-restore path)
            self._d_temp = self._d_temp.at[b].set(np.float32(temp))
            self._d_topk = self._d_topk.at[b].set(np.int32(topk))
            self._d_topp = self._d_topp.at[b].set(np.float32(topp))
            self._d_last_tok = self._d_last_tok.at[b].set(np.int32(last))

        ops["samprow"] = op_samprow

        def op_snap(b, Lb, start, srcs):
            # host copies of slot b's committed KV rows [start, Lb); the
            # physical-table indirection rides the payload as (in_arena,
            # row, off) triples so followers slice the same sources
            bt = self._paging.block_tokens

            def cut(arr, pool):
                if isinstance(arr, dict):
                    if not arr:  # fused GQA: "v" is the empty-dict placeholder
                        return {}
                    return {
                        k: cut(arr[k], None if pool is None else pool[k])
                        for k in arr
                    }
                if srcs is None:
                    return self._fetch(arr[:, b : b + 1, :, start:Lb])
                parts = [
                    arr[:, row : row + 1, :, off : off + bt]
                    if in_arena
                    else pool[:, row : row + 1]
                    for in_arena, row, off in srcs
                ]
                whole = jnp.concatenate(parts, axis=3) if len(parts) > 1 else parts[0]
                return self._fetch(whole[:, :, :, start:Lb])

            return cut(self._ck, self._pool_k), cut(self._cv, self._pool_v)

        ops["snap"] = op_snap

        def op_pfxput(eid, slot, p0):
            # park a slot's prefix rows [0, p0) as a device prefix entry
            pk = _tree2(lambda c, _: c[:, slot : slot + 1, :, :p0], self._ck, self._ck)
            pv = _tree2(lambda c, _: c[:, slot : slot + 1, :, :p0], self._cv, self._cv)
            self._x_prefix[eid] = (pk, pv)
            return pk, pv

        ops["pfxput"] = op_pfxput

        def op_pfxdrop(eid):
            self._x_prefix.pop(eid, None)

        ops["pfxdrop"] = op_pfxdrop

        def op_pfximp(eid, hk, hv):
            # fleet-tier import: wire-decoded host rows become a device
            # entry (replicated under GSPMD — any consistent placement
            # works; insert jits reshard on use)
            up = self._put_repl if self._spmd else jnp.asarray
            pk = jax.tree.map(up, hk)
            pv = jax.tree.map(up, hv)
            self._x_prefix[eid] = (pk, pv)
            return pk, pv

        ops["pfximp"] = op_pfximp

        def op_pfxexp(eid):
            pk, pv = self._x_prefix[eid]
            return self._fetch((pk, pv))

        ops["pfxexp"] = op_pfxexp

        def op_poolexp(prows, p0):
            # physical-entry export: gather the entry's pool rows into one
            # contiguous [L, 1, H, p0, ...] host tree (dict-aware)
            def cut(pool):
                if isinstance(pool, dict):
                    if not pool:
                        return {}
                    return {k: cut(pool[k]) for k in pool}
                parts = [pool[:, r : r + 1] for r in prows]
                whole = jnp.concatenate(parts, axis=3) if len(parts) > 1 else parts[0]
                return self._fetch(whole[:, :, :, :p0])

            return cut(self._pool_k), cut(self._pool_v)

        ops["poolexp"] = op_poolexp

        def op_cow(slot, blk, prow):
            self._ck, self._cv = self._cow_fn(
                self._ck, self._cv, self._pool_k, self._pool_v,
                np.int32(slot), np.int32(blk), np.int32(prow),
            )

        ops["cow"] = op_cow

        def op_pput(kind, a, b, prow):
            # prefix-pool row stores: "arena" copies a slot block (a=row,
            # b=off), "pool" copies a pool row (a=src_row), "host" uploads a
            # wire-decoded block (a=hk, b=hv)
            if kind == "arena":
                self._pool_k, self._pool_v = self._pool_arena_fn(
                    self._pool_k, self._pool_v, self._ck, self._cv,
                    np.int32(a), np.int32(b), np.int32(prow),
                )
            elif kind == "pool":
                self._pool_k, self._pool_v = self._pool_pool_fn(
                    self._pool_k, self._pool_v, np.int32(a), np.int32(prow)
                )
            else:
                self._pool_k, self._pool_v = self._pool_host_fn(
                    self._pool_k, self._pool_v, a, b, np.int32(prow)
                )

        ops["pput"] = op_pput

        return ops

    # -- jit builders ------------------------------------------------------

    def _build_decode(self):
        cfg = self.cfg
        K = self.decode_chunk
        mask = self._allowed_mask
        impl = self.decode_impl
        base_key = self._base_key

        def decode_body(params, ck, cv, packed, d_temp, d_topk, d_topp,
                        d_last, compact, paged=None):
            """One decode round (K fused steps) — traced body shared by
            decode_chunk_fn and fused_step_fn.

            All per-round host inputs ride ONE packed i32 transfer (on a
            remote-TPU tunnel every separate transfer/dispatch is tens of
            ms): compact → [lengths | slot_ids | counter] (2*Ba+1), full →
            [lengths | counter] (B+1). The round's INPUT TOKENS never touch
            the host: they come from `d_last`, the device-resident
            last-token ring that this round (and admissions) write — so the
            NEXT round can be dispatched before this one's output is ever
            fetched, and the decode chain rides the device stream while the
            host trails behind fetching outputs for emission (the pipelined
            loop, _run). The RNG key derives from the counter on device;
            sampling params are the device-resident arrays, gathered by
            slot id on the compact path (row i serves cache row
            slot_ids[i] — _dispatch_decode)."""
            if compact:
                Ba = (packed.shape[0] - 1) // 2
                lengths = packed[:Ba]
                slot_ids = packed[Ba : 2 * Ba]
                tokens = d_last[slot_ids]
                temp = d_temp[slot_ids]
                topk = d_topk[slot_ids]
                topp = d_topp[slot_ids]
            else:
                Ba = packed.shape[0] - 1
                lengths = packed[:Ba]
                slot_ids = None
                tokens = d_last
                temp, topk, topp = d_temp, d_topk, d_topp
            rng = jax.random.fold_in(base_key, packed[-1])

            def step(carry, _):
                ck, cv, toks, lens, rng = carry
                logits, ck, cv = llama_decode_step(
                    cfg, params, ck, cv, toks, lens, attn_impl=impl,
                    slot_ids=slot_ids, paged=paged,
                )
                if mask is not None:
                    logits = jnp.where(mask, logits, -jnp.inf)
                rng, sub = jax.random.split(rng)
                # parked rows (lens >= S) carry stale params from a prior
                # occupant — exclude them from fast-path selection
                S_cache = (ck["q"] if isinstance(ck, dict) else ck).shape[3]
                new = sample_tokens(
                    logits, sub, temp, topk, topp, active=lens < S_cache
                )
                return (ck, cv, new, lens + 1, rng), new

            (ck, cv, last, _, _), out = jax.lax.scan(
                step, (ck, cv, tokens, lengths, rng), None, length=K
            )
            # write the round's final tokens back into the ring. Compact pad
            # rows all target the same inactive row (duplicate-index set:
            # last write wins on garbage) — harmless, admission overwrites
            # on reuse and the device stream is in-order.
            if compact:
                d_last = d_last.at[slot_ids].set(last)
            else:
                d_last = last
            return out, ck, cv, d_last  # out: [K, Ba]

        @partial(jax.jit, donate_argnums=(1, 2, 7), static_argnames=("compact",),
                 **self._shard_out(["repl", "k", "v", "repl"]))
        def decode_chunk_fn(params, ck, cv, packed, d_temp, d_topk, d_topp,
                            d_last, compact, paged=None):
            return decode_body(params, ck, cv, packed, d_temp, d_topk,
                               d_topp, d_last, compact, paged=paged)

        @partial(
            jax.jit, donate_argnums=(1, 2, 7),
            static_argnames=("compact", "skey"),
            **self._shard_out(["repl", "repl", "k", "v", "repl"]),
        )
        def fused_step_fn(params, ck, cv, packed, d_temp, d_topk, d_topp,
                          d_last, p_tokens, p_slots, p_starts, p_nvalid,
                          compact, skey, paged=None):
            """Fused scheduler step: one decode round (K steps for the
            active rows) AND one budget-bounded prefill chunk group in the
            SAME dispatch (the token-budget scheduler's stall-free shape —
            decode cadence never waits behind a host-paced prefill phase,
            and the chunk group costs at most ~one extra decode round of
            device time by budget construction).

            Decode rows and the chunk group's slots are DISJOINT (mid-
            prefill slots are reserved, parked at length=S, and never in the
            active set), so running the chunk after the decode scan on the
            threaded cache is value-identical to two separate dispatches.
            The prefill logits return un-fetched; activation samples from
            them only when a prompt's last chunk landed."""
            out, ck, cv, d_last = decode_body(
                params, ck, cv, packed, d_temp, d_topk, d_topp, d_last,
                compact, paged=paged,
            )
            p_logits, ck, cv = llama_prefill_chunk_batch(
                cfg, params, ck, cv, p_tokens, p_slots, p_starts, p_nvalid,
                skey=skey, paged=paged,
            )
            return out, p_logits, ck, cv, d_last

        @partial(
            jax.jit, donate_argnums=(1, 2, 7),
            static_argnames=("compact", "skey"),
            **self._shard_out(["repl", "repl", "k", "v", "repl"]),
        )
        def fused_ragged_fn(params, ck, cv, packed, d_temp, d_topk, d_topp,
                            d_last, p_tokens, p_rowids, p_positions, p_slots,
                            p_starts, p_last_idx, compact, skey, paged=None):
            """fused_step_fn's ragged twin: the chunk group rides the packed
            token buffer + per-row descriptors instead of [Ab, bucket] pads,
            so ONE executable per (T, compact) covers every fill mix (the
            bucketed zoo minted one per (Ab, bucket, skey)). Same disjoint-
            slot argument as fused_step_fn."""
            out, ck, cv, d_last = decode_body(
                params, ck, cv, packed, d_temp, d_topk, d_topp, d_last,
                compact, paged=paged,
            )
            p_logits, ck, cv = llama_prefill_chunk_ragged(
                cfg, params, ck, cv, p_tokens, p_rowids, p_positions,
                p_slots, p_starts, p_last_idx, skey=skey, paged=paged,
            )
            return out, p_logits, ck, cv, d_last

        return decode_chunk_fn, fused_step_fn, fused_ragged_fn

    def _build_verify(self):
        """Jitted speculative verify: ONE model call over [token, draft_1..
        draft_K] per slot through the chunked-prefill machinery (multi-
        position KV writes for free), full-position logits, then
        accept/reject + the follow-on sample on device (spec_verify). Only
        two [A] int arrays (accepted counts, final tokens) ever reach the
        host — the accepted drafts themselves are already known host-side.

        Pad rows carry slot id B: every cache scatter and the token-ring
        write drop out of bounds (the admission-path invariant), and their
        clamped param gathers are excluded from the sampler's homogeneity
        reductions via `active`."""
        cfg = self.cfg
        mask = self._allowed_mask
        base_key = self._base_key
        B = self.max_slots

        @partial(jax.jit, donate_argnums=(1, 2, 3), static_argnames=("skey",),
                 **self._shard_out(["repl", "repl", "k", "v", "repl"]))
        def verify_fn(params, ck, cv, d_last, d_temp, d_topk, d_topp,
                      tokens, slots, starts, nvalid, drafts, ndraft,
                      counter, skey, paged=None, cn=None):
            logits, ck, cv = llama_prefill_chunk_batch(
                cfg, params, ck, cv, tokens, slots, starts, nvalid,
                skey=skey, all_logits=True, paged=paged,
            )  # [A, C, V]
            if mask is not None:
                logits = jnp.where(mask, logits, -jnp.inf)
            # constrained verify rounds: per-POSITION automaton masks
            # ([A, C, W] — row j constrains the token at draft offset j)
            # applied BEFORE accept/reject, so the draft acceptance test
            # and the rejection-resampling residual both see the
            # renormalized masked target — distribution-exact under the
            # constraint. cn=None (unconstrained rounds) keeps the
            # pre-existing executable (the paged=None trailing pattern).
            if cn is not None:
                logits = apply_token_mask(logits, cn[0], cn[1], cn[2])
            temp = d_temp[slots]
            topk = d_topk[slots]
            topp = d_topp[slots]
            rng = jax.random.fold_in(base_key, counter)
            n_acc, final = spec_verify(
                logits, drafts, ndraft, rng, temp, topk, topp,
                active=slots < B, exact=cn is not None,
            )
            # the round's final token into the device ring: the next decode
            # round reads its input from d_last without host staging
            d_last = d_last.at[slots].set(final)
            return n_acc, final, ck, cv, d_last

        return verify_fn

    def _build_cn_step(self):
        """Masked SINGLE-step decode for constrained slots (op "cnstep").

        Constrained slots cannot ride the K-step pipelined scan: the mask
        for step j+1 depends on the token sampled at step j, which only the
        host-side automaton can produce. So constrained traffic decodes one
        committed-exact masked step per loop iteration — compact packed
        [lengths | slot_ids | counter] exactly like decode_body's compact
        path, plus the packed [Ba, W] mask rows and [Ba, NB] bias arrays.
        Built lazily on the first constrained dispatch; under
        TPU_CONSTRAIN=0 it never exists (zero-trace kill switch)."""
        cfg = self.cfg
        mask = self._allowed_mask
        impl = self.decode_impl
        base_key = self._base_key

        @partial(jax.jit, donate_argnums=(1, 2, 7),
                 **self._shard_out(["repl", "k", "v", "repl"]))
        def cn_step_fn(params, ck, cv, packed, d_temp, d_topk, d_topp,
                       d_last, masks, bids, bvals, paged=None):
            Ba = (packed.shape[0] - 1) // 2
            lengths = packed[:Ba]
            slot_ids = packed[Ba : 2 * Ba]
            tokens = d_last[slot_ids]
            temp = d_temp[slot_ids]
            topk = d_topk[slot_ids]
            topp = d_topp[slot_ids]
            rng = jax.random.fold_in(base_key, packed[-1])
            logits, ck, cv = llama_decode_step(
                cfg, params, ck, cv, tokens, lengths, attn_impl=impl,
                slot_ids=slot_ids, paged=paged,
            )
            if mask is not None:
                logits = jnp.where(mask, logits, -jnp.inf)
            logits = apply_token_mask(logits, masks, bids, bvals)
            S_cache = (ck["q"] if isinstance(ck, dict) else ck).shape[3]
            new = sample_tokens(
                logits, rng, temp, topk, topp, active=lengths < S_cache,
                exact=True,
            )
            d_last = d_last.at[slot_ids].set(new)
            return new, ck, cv, d_last

        return cn_step_fn

    def stall_seconds(self) -> float:
        """Age of the engine loop's last progress stamp. Large values with
        in-flight work mean the thread is wedged inside an uninterruptible
        device call (serving layer: flip the device offline, fail over).
        Zero while a first-time executable shape may still be compiling."""
        if time.time() < self._compile_grace_until:
            return 0.0
        return max(0.0, time.time() - self.last_progress)

    def _watchdog(self) -> None:
        poll = min(30.0, max(1.0, self.stall_timeout_s / 4))
        while not self._stop_evt.wait(timeout=poll):
            self.check_anomalies()  # decode-cadence stall, paged-leak growth
            age = self.stall_seconds()
            if age > self.stall_timeout_s:
                if not self.stalled:
                    self.stalled = True
                    self._watchdog_transition("stalled")
                    log.error(
                        "engine stalled: no loop progress for %.0f s "
                        "(wedged device call?); shedding queued load", age,
                    )
                # Drain requests the blocked loop can never admit — their
                # consumers would hang past any reasonable client timeout.
                # Re-check staleness per pop: if the loop resumed we must
                # not steal legitimate requests.
                drained = 0
                while self.stall_seconds() > self.stall_timeout_s:
                    try:
                        req = self._admit.get_nowait()
                    except queue.Empty:
                        break
                    self._count_error()
                    req.out.put(
                        {"type": "error",
                         "error": "engine stalled: accelerator unresponsive"}
                    )
                    req.out.put(_DONE)
                    drained += 1
                if drained:
                    self._watchdog_transition("shed")
                    log.error("engine watchdog errored %d queued requests", drained)
                # In-flight consumers must not hang forever either: deliver
                # their terminal errors now. The wedged loop cannot race us
                # (it is blocked inside a device call); if it resumes
                # anyway, the aborted flag + identity guards turn its later
                # emissions into no-ops against dead queues, and the slots
                # self-clean through the normal finish path.
                for s in list(self._slots):
                    if (
                        s is not None and not s.aborted and not s.done
                        and self.stall_seconds() > self.stall_timeout_s
                    ):
                        s.aborted = True
                        self._count_error()
                        s.req.out.put(
                            {"type": "error",
                             "error": "engine stalled: accelerator unresponsive"}
                        )
                        s.req.out.put(_DONE)
                for st in list(self._prefills.values()):
                    if (
                        not st.aborted
                        and self.stall_seconds() > self.stall_timeout_s
                    ):
                        st.aborted = True
                        self._count_error()
                        st.req.out.put(
                            {"type": "error",
                             "error": "engine stalled: accelerator unresponsive"}
                        )
                        st.req.out.put(_DONE)
                # preempted-and-offloaded requests wait on restore, which the
                # wedged loop will never perform — their consumers must not
                # hang either (pool.drain() removes the snapshots, so a
                # resuming loop cannot double-deliver)
                if self._pool is not None and (
                    self.stall_seconds() > self.stall_timeout_s
                ):
                    for snap in self._pool.drain():
                        self._paging.drop_snap(snap.snap_id)
                        s = snap.slot_obj
                        if s is None or s.aborted or s.done:
                            continue
                        s.aborted = True
                        self._count_error()
                        s.req.out.put(
                            {"type": "error",
                             "error": "engine stalled: accelerator unresponsive"}
                        )
                        s.req.out.put(_DONE)
                    self._phys_sweep()
            elif self.stalled:
                self.stalled = False
                self._watchdog_transition("recovered")
                log.warning("engine loop recovered after stall")

    def _next_counter(self) -> int:
        """RNG stream position. The hot paths ship the counter inside their
        packed int transfer and fold it into the base key ON DEVICE — a
        host-side fold_in is one more dispatch per round."""
        self._rng_counter += 1
        return self._rng_counter

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "GenerationEngine":
        if self._thread is None:
            # leader-side channel setup first (blocking accept of every
            # follower) — the loop must never emit into a half-built channel
            self._backend.start()
            self._thread = threading.Thread(target=self._run, name="gen-engine", daemon=True)
            self._thread.start()
        return self

    def shutdown(self) -> None:
        self._stop_evt.set()
        self._wake.set()
        if self._warmup is not None:
            # stop the background AOT thread first: a compile in flight
            # holds jit internals the teardown below must not race
            self._warmup.stop()
        if self._thread:
            self._thread.join(timeout=10)
            self._thread = None
        # release the followers (idempotent — the loop tail already sent
        # stop on a dead engine) and drop the command channel
        self._backend.stop()
        self._backend.close()
        # Drain every waiter — callers blocked in req.out.get() must not
        # deadlock when the engine stops mid-request.
        self._abort_all("engine shutdown")
        while True:
            try:
                req = self._admit.get_nowait()
            except queue.Empty:
                break
            req.out.put({"type": "error", "error": "engine shutdown"})
            req.out.put(_DONE)
        while self._migrate_in is not None and not self._migrate_in.empty():
            # migrated-in snapshots never restored: their consumers block
            # on queues this engine now owns — error them like queued work
            try:
                _snap, _header, _nbytes, s = self._migrate_in.get_nowait()
            except queue.Empty:
                break
            s.req.out.put({"type": "error", "error": "engine shutdown"})
            s.req.out.put(_DONE)

    # -- warmup (executor/warmup.py; ROADMAP item 5) -----------------------

    def warmup_shape_zoo(self) -> list[tuple[str, tuple]]:
        """The engine's serving-shape zoo: the (phase, shape key) pairs its
        config can dispatch, in `_note_exec_shape`'s own vocabulary — the
        same keys the CompileLedger aggregates, so an imported warmup plan
        (prior boots' measurements) matches these entries by string.

        Enumeration is DELIBERATELY first-hit-bounded, not exhaustive:
        admit and decode ladders are small and fully listed; chunked
        prefill lists only the zero-context skey (every boot's first long
        prompt — later skeys depend on live context lengths and ride the
        ledger priors instead); fused/verify depend on the live fill mix
        and never enumerate from config (warmup.py PLANNABLE_PHASES)."""
        zoo: list[tuple[str, tuple]] = []
        phys = self._phys is not None
        S = self.max_seq_len
        buckets: list[int] = []
        n = 1
        while True:
            b = self._bucket(n)
            if not buckets or b > buckets[-1]:
                buckets.append(b)
            if b >= S:
                break
            n = b + 1
        ab_cap = 1 << max(0, self.admit_batch - 1).bit_length()
        ab = 1
        while ab <= ab_cap:
            for bk in buckets:
                zoo.append(("admit", (ab, bk)))
            ab <<= 1
        B = self.max_slots
        if self.decode_compact:
            ba = min(8, B)
            while ba < B:
                zoo.append(("decode", (ba, True, phys)))
                ba <<= 1
        zoo.append(("decode", (B, False, phys)))
        if self.ragged_prefill and self._ragged_cap:
            skey0 = 0 if self._ragged_impl == "kernel" else min(128, S)
            t = min(32, self._ragged_cap)
            while t <= self._ragged_cap:
                zoo.append(("pf_rag", (t, skey0, phys)))
                t <<= 1
        elif self.prefill_chunk > 0:
            skey0 = min(128, S)
            cap = self._bucket(self.prefill_chunk)
            rows = 1
            while rows <= ab_cap:
                for bk in [b for b in buckets if b <= cap]:
                    zoo.append(("chunk", (rows, bk, skey0, phys)))
                rows <<= 1
        return zoo

    @staticmethod
    def parse_ledger_key(ks: str) -> tuple:
        """Invert `_compile_obs`'s colon-joined key encoding back into a
        typed tuple — shape keys only ever carry ints and bools (the
        dispatch-surface lint pins the vocabulary), so the round-trip is
        exact for every real ledger row."""
        out: list = []
        for part in ks.split(":"):
            if part == "True":
                out.append(True)
            elif part == "False":
                out.append(False)
            else:
                try:
                    out.append(int(part))
                except ValueError:
                    out.append(part)
        return tuple(out)

    def _warmup_key_fits(self, phase: str, key: tuple) -> bool:
        """Whether a plan step's shape key is dispatchable by THIS engine's
        config. The compile ledger is process-shared and warmup packs ship
        between hosts, so priors can carry shapes from other configs — an
        admit bucket beyond max_seq_len fails to lower (the cache operand
        is too small), a decode batch beyond max_slots was never built.
        Out-of-config keys record skip, like the phys-flag mismatches."""
        try:
            cap = self._bucket(self.max_seq_len)
            ab_cap = 1 << max(0, self.admit_batch - 1).bit_length()
            if phase == "admit":
                ab, bucket = int(key[0]), int(key[1])
                return (1 <= ab <= ab_cap and 0 < bucket <= cap
                        and self._bucket(bucket) == bucket)
            if phase == "decode":
                return 1 <= int(key[0]) <= self.max_slots
            if phase == "chunk":
                rows, bucket, skey = int(key[0]), int(key[1]), int(key[2])
                return (self.prefill_chunk > 0 and 1 <= rows <= ab_cap
                        and 0 < bucket <= cap and 0 <= skey <= cap)
            if phase == "pf_rag":
                t, skey = int(key[0]), int(key[1])
                return (bool(self.ragged_prefill and self._ragged_cap)
                        and 1 <= t <= self._ragged_cap and 0 <= skey <= cap)
            return True
        except (TypeError, ValueError, IndexError):
            return False

    def warmup_compile(self, phase: str, key: tuple) -> float | None:
        """AOT-compile one executable shape via jit lower().compile() —
        the warmup planner's compile hook. This populates the persistent
        XLA compile cache (TPU_COMPILE_CACHE), NOT jit's dispatch cache:
        the first real dispatch of the shape still traces, then
        deserializes the cached executable in well under TPU_COMPILE_HIT_S
        instead of paying the 1-2 min XLA compile. Returns the compile
        wall, or None for phases whose argument shapes cannot be
        synthesized from the key alone (fused/verify/restore — they
        compile on first real dispatch, exactly as before warmup).

        ShapeDtypeStruct mirrors of the live params/cache/sampling arrays
        carry their committed shardings so the lowered module (and its
        cache key) matches what the serve path will build."""
        if phase not in ("admit", "chunk", "decode", "pf_rag"):
            return None
        if not self._warmup_key_fits(phase, key):
            return None  # stale prior from a different engine config

        def sds(tree):
            return jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None)
                ),
                tree,
            )

        def host(shape, dtype=jnp.int32):
            return jax.ShapeDtypeStruct(shape, dtype)

        paged = None
        if self._phys is not None:
            paged = sds(self._paged_from(self._paged_payload()))
        t0 = time.perf_counter()
        P, CK, CV = sds(self.params), sds(self._ck), sds(self._cv)
        if phase == "admit":
            ab, bucket = int(key[0]), int(key[1])
            self._admit_fn.lower(
                P, CK, CV, sds(self._d_temp), sds(self._d_topk),
                sds(self._d_topp), sds(self._d_last_tok),
                host((ab, bucket)), host((3 * ab + 2,)),
                host((2 * ab,), jnp.float32),
            ).compile()
        elif phase == "decode":
            ba, compact = int(key[0]), bool(key[1])
            if bool(key[2]) != (self._phys is not None):
                return None  # stale prior from a different pool config
            packed = host(((2 * ba + 1,) if compact else (self.max_slots + 1,)))
            self._decode_fn.lower(
                P, CK, CV, packed, sds(self._d_temp), sds(self._d_topk),
                sds(self._d_topp), sds(self._d_last_tok),
                compact=compact, paged=paged,
            ).compile()
        elif phase == "chunk":
            rows, bucket, skey = int(key[0]), int(key[1]), int(key[2])
            if bool(key[3]) != (self._phys is not None):
                return None
            self._prefill_chunk_fn.lower(
                P, CK, CV, host((rows, bucket)), host((rows,)),
                host((rows,)), host((rows,)), skey=skey, paged=paged,
            ).compile()
        else:  # pf_rag
            t, skey = int(key[0]), int(key[1])
            if bool(key[2]) != (self._phys is not None):
                return None
            rows = max(1, self.admit_batch)
            self._ragged_chunk_fn.lower(
                P, CK, CV, host((t,)), host((t,)), host((t,)),
                host((rows,)), host((rows,)), host((rows,)),
                skey=skey, paged=paged,
            ).compile()
        wall = time.perf_counter() - t0
        self._compile_obs(phase, key, wall, src="warmup")
        return wall

    def start_warmup(self, priors: list[dict] | None = None):
        """Build and run the warmup plan (TPU_WARMUP=0: a TRUE no-op —
        returns None, no planner, no compiles, greedy output is
        token-identical either way). The critical first-token prefix (one
        admit bucket + one prefill executable + one decode shape) compiles
        SYNCHRONOUSLY before this returns; the rest of the zoo compiles on
        a low-priority background thread while the engine serves
        (TPU_WARMUP_BG=0 skips it). `priors` takes CompileLedger table
        rows — the live ledger's, or an imported warmup pack's — to order
        the plan by measured compile cost x hit count. Idempotent."""
        from . import warmup as warmup_mod

        if not warmup_mod.warmup_enabled():
            return None
        if self._warmup is not None:
            return self._warmup
        rows = list(priors or [])
        rows.extend(self._ledger.table())
        prior_idx = warmup_mod.priors_from_table(rows)
        zoo = self.warmup_shape_zoo()
        for (ph, ks) in list(prior_idx):
            # measured shapes from prior boots join the zoo with exact
            # typed keys; unplannable phases ride along and record as skip
            key = self.parse_ledger_key(ks)
            if (ph, key) not in zoo:
                zoo.append((ph, key))
        steps = warmup_mod.plan_steps(zoo, prior_idx)
        self._warmup = warmup_mod.WarmupPlanner(
            self.warmup_compile, steps,
            throttle_s=float(os.environ.get("TPU_WARMUP_THROTTLE_S", "0.05") or 0),
            event=self._flight.event,
        )
        self._warmup.run_critical()
        if warmup_mod.warmup_bg_enabled():
            self._warmup.start_background()
        else:
            for s in self._warmup.steps:
                if s.status == "pending":
                    s.status = "skip"
            self._warmup.start_background()  # immediate fully_warm
        return self._warmup

    def warmup_priors(self) -> list[dict]:
        """This engine's compile-ledger rows in warmup-prior form — what
        the model zoo captures at swap-out so the NEXT residency's
        start_warmup() re-plans from measured compile cost × hit count
        (executor/warmup.py: pack_priors)."""
        from . import warmup as warmup_mod

        return warmup_mod.pack_priors(self._ledger.table())

    def warmup_stats(self) -> dict[str, Any]:
        """Readiness + plan progress for /v1/debug/warmup and the router's
        warming tag. No planner (warmup off / plain test boot) reads as
        fully_warm with zero steps: an unwarmed engine routes exactly as
        the pre-warmup era."""
        if self._warmup is None:
            return {"state": "fully_warm", "steps": 0, "enabled": False}
        st = self._warmup.stats()
        st["enabled"] = True
        return st

    # -- public API --------------------------------------------------------

    def submit(self, req: GenRequest) -> GenRequest:
        if self.dead:
            req.out.put(
                {"type": "error", "error": f"engine dead: {self.dead}"}
            )
            req.out.put(_DONE)
            return req
        if self._stop_evt.is_set():
            req.out.put({"type": "error", "error": "engine shutdown"})
            req.out.put(_DONE)
            return req
        if self.stalled:
            # fail fast instead of queueing behind a wedged device call —
            # the router sees the device offline and falls back to cloud
            self._count_error()
            req.out.put(
                {"type": "error", "error": "engine stalled: accelerator unresponsive"}
            )
            req.out.put(_DONE)
            return req
        self._admit.put(req)
        self._wake.set()
        return req

    def generate_stream(
        self,
        prompt: str,
        *,
        max_tokens: int = 256,
        temperature: float = 0.7,
        top_k: int = 0,
        top_p: float = 1.0,
        stop: list[str] | None = None,
        priority: int = 0,
        tenant: str = "",
        constraint: dict | None = None,
        logit_bias: list | None = None,
    ) -> Iterator[dict[str, Any]]:
        """Yield {"type":"token","text":...} events then a final
        {"type":"done", "usage":..., "finish_reason":...}."""
        ids = self.tokenizer.encode(prompt)
        req = GenRequest(
            prompt_ids=ids,
            max_tokens=max_tokens,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            stop=stop or [],
            priority=priority,
            trace_ctx=tracing.current_traceparent(),
            tenant=tenant,
            constraint=constraint,
            logit_bias=logit_bias,
        )
        self.submit(req)
        while True:
            evt = req.out.get()
            if evt is _DONE:
                return
            yield evt
            if evt.get("type") == "done":
                return

    def generate(self, prompt: str, **kw: Any) -> dict[str, Any]:
        """Non-streaming: returns {"text", "usage", "finish_reason"}."""
        text_parts: list[str] = []
        final: dict[str, Any] = {}
        for evt in self.generate_stream(prompt, **kw):
            if evt["type"] == "token":
                text_parts.append(evt["text"])
            elif evt["type"] == "done":
                final = evt
            elif evt["type"] == "error":
                raise RuntimeError(evt.get("error", "generation failed"))
        return {
            "text": "".join(text_parts),
            "usage": final.get("usage", {}),
            "finish_reason": final.get("finish_reason", "stop"),
        }

    def prefix_cache_stats(self) -> dict[str, int]:
        """Snapshot for dashboards/metrics (the cache itself is engine-thread
        private state — callers must not reach into it)."""
        return {
            "entries": len(self._prefix_cache),
            "bytes": self._prefix_cache_bytes,
            "hits": self.prefix_cache_hits,
            "misses": self.prefix_cache_misses,
        }

    def phase_budget(self) -> dict[str, float]:
        """Accumulated engine-loop wall-clock seconds per phase. Snapshot at
        two points and subtract to budget a window (bench.py serve output)."""
        return dict(self._phase_s)

    def ttft_percentiles(
        self, window_s: float = 600.0
    ) -> tuple[float, float, int]:
        """(p50_ms, p95_ms, n) of client-observed TTFT over the recent
        window — nearest-rank, matching scripts/probe_models.py."""
        now = time.time()
        with self.stats_lock:
            vals = sorted(v for t, v in self._ttft_window if now - t <= window_s)
        if not vals:
            return 0.0, 0.0, 0
        n = len(vals)
        p50 = vals[max(0, (n + 1) // 2 - 1)]
        p95 = vals[max(0, min(n - 1, int(n * 0.95 + 0.5) - 1))]
        return p50, p95, n

    def scheduler_stats(self) -> dict[str, float]:
        """Token-budget scheduler observability (telemetry/metrics.py gauges
        + the starvation counter): the live prefill token budget, decode
        batch occupancy, and cost-model EMAs."""
        out = self._sched.stats()
        out["decode_batch_occupancy"] = (
            self._last_active_n / self.max_slots if self.max_slots else 0.0
        )
        return out

    def scheduler_tenant_stats(self) -> dict[str, dict[str, float]]:
        """Per-tenant quota detail (token-bucket level, throttle and
        charge counters) for /v1/debug/perf. Empty without quotas."""
        return self._sched.tenant_stats()

    def speculation_stats(self) -> dict[str, float]:
        """Self-speculative decoding observability (telemetry/metrics.py
        gauges + the engines_info speculation block): cumulative drafted /
        accepted / emitted token counts, verify-call count, and the derived
        acceptance rate and tokens-per-verify-call."""
        drafted = float(self.spec_drafted)
        calls = float(self.spec_calls)
        return {
            "enabled": 1.0 if self._verify_fn is not None else 0.0,
            "k": float(self.spec_k),
            "min_ngram": float(self.spec_min_ngram),
            "drafted_tokens": drafted,
            "accepted_tokens": float(self.spec_accepted),
            "emitted_tokens": float(self.spec_emitted),
            "verify_calls": calls,
            "accept_rate": (self.spec_accepted / drafted) if drafted else 0.0,
            "tok_per_call": (self.spec_emitted / calls) if calls else 0.0,
        }

    def constrain_stats(self) -> dict[str, Any]:
        """Constrained-decoding observability (/v1/debug/constrain + the
        bench line of record): traffic counters, the token-level validity
        proof (illegal_tokens must be 0 — the mask makes illegal emission
        impossible by construction; the counter is the check), per-token
        host mask cost, spec-composition acceptance, and the schema
        compile-cache economics."""
        toks = float(self.cn_tokens)
        fin = float(self.cn_finished)
        drafted = float(self.cn_spec_drafted)
        out: dict[str, Any] = {
            "enabled": 1.0 if self._constrain is not None else 0.0,
            "requests": float(self.cn_requests),
            "tokens": toks,
            "illegal_tokens": float(self.cn_illegal),
            "finished": fin,
            "finished_accepting": float(self.cn_finished_accepting),
            # token-level validity: every constrained token was automaton-
            # legal AND every finished constrained request ended accepting
            "schema_valid_rate": (
                (self.cn_finished_accepting / fin) if fin else 1.0
            ) if self.cn_illegal == 0 else 0.0,
            "mask_us_per_tok": (self.cn_mask_s * 1e6 / toks) if toks else 0.0,
            "spec_drafted": drafted,
            "spec_accepted": float(self.cn_spec_accepted),
            "spec_accept_rate": (
                (self.cn_spec_accepted / drafted) if drafted else 0.0
            ),
        }
        if self._constrain is not None:
            out["cache"] = self._constrain.stats()
        return out

    def _offered_load(self) -> float:
        """Offered load the admission watermark compares against, in
        slot-equivalents. Only meaningful with the pool on.

        Paged accounting (paging.py:offered_blocks): unique blocks
        referenced by live tables and parked snapshots count ONCE — shared
        prefixes are paid for once no matter how many slots pin them — plus
        each request's committed decode growth (`wants`: length + tokens
        remaining + one decode chunk, the promise admission already made),
        snapshot restore needs, and the admit queue priced at the EMA
        private-block cost. With zero sharing this reduces exactly to the
        old integer `occupied + queued + preempted` accounting."""
        queued = self._admit.qsize()
        if self._pool is None:
            return float(self.slots_in_use() + queued)
        mgr = self._paging
        S = self.max_seq_len
        K = self.decode_chunk
        wants: dict[int, int] = {}
        for b, s in enumerate(self._slots):
            if s is None or s.done or s.aborted:
                continue
            rem = max(0, s.req.max_tokens - s.generated)
            wants[b] = min(int(self._lengths[b]) + rem + K, S)
        for slot, st in list(self._prefills.items()):
            if st.aborted:
                continue
            wants[slot] = min(len(st.ids) + max(0, st.req.max_tokens) + K, S)
        return mgr.offered_blocks(wants, queued) / max(1, mgr.blocks_per_slot)

    def memory_stats(self) -> dict[str, float]:
        """KV pool observability (engines_info memory block + dashboard +
        llmtpu_kv_* metrics). {"enabled": 0.0} when TPU_KV_HOST_OFFLOAD is
        off — the pool doesn't exist and nothing else is meaningful."""
        pool = self._pool
        if pool is None:
            return {"enabled": 0.0}
        out = pool.stats()
        out["enabled"] = 1.0
        offered = self._offered_load()
        out["offered"] = float(offered)
        out["headroom"] = pool.headroom(offered)
        return out

    def paging_stats(self) -> dict[str, float]:
        """Paged-KV block economy (engines_info paging block + dashboard +
        llmtpu_kv_block* metrics). Always available — the ledger is pure
        host bookkeeping and runs regardless of the pool."""
        out = self._paging.stats()
        out["enabled"] = 1.0
        out["leaks"] = float(self._paging.leak_count())
        if self._phys is not None:
            out.update(self._phys.stats())
            out["physical"] = 1.0
            contig, phys = self._phys_hbm_peak
            out["hbm_bytes_contiguous_equiv_peak"] = contig
            out["hbm_bytes_physical_peak"] = phys
            out["hbm_bytes_ratio_peak"] = self._phys_hbm_peak_ratio
        else:
            out["physical"] = 0.0
        return out

    def admission_state(self, tenant: str = "") -> tuple[bool, float]:
        """(shed, retry_after_s) for the API's load-shedding gate. SIDE-
        EFFECT FREE except the tenant-quota throttle counter — dashboards
        and the jobs claim path call the zero-arg form; only a caller that
        actually rejects work records it via note_shed(). A non-empty
        `tenant` additionally consults that tenant's token-bucket quota
        (scheduler.tenant_admit): over-quota tenants shed HERE, per
        tenant, even while the pool itself has headroom. (False, 0.0)
        with zero pool bookkeeping when pool and quotas are both off."""
        if tenant:
            ok, retry = self._sched.tenant_admit(tenant)
            if not ok:
                return True, min(600.0, max(1.0, retry))
        pool = self._pool
        if pool is None:
            return False, 0.0
        offered = self._offered_load()
        if pool.admit_ok(offered):
            return False, 0.0
        with self.stats_lock:
            fr, ft = self.finished_requests, self.finished_tokens
        mean_tokens = (ft / fr) if fr else 64.0
        n_waiting = self._admit.qsize() + pool.preempted_count()
        retry = self._sched.drain_estimate_s(
            max(1, n_waiting), mean_tokens, self.decode_chunk, self.max_slots
        )
        return True, min(600.0, max(1.0, retry))

    def note_shed(self, n: int = 1, tenant: str = "") -> None:
        """Record that the API shed work on this engine's behalf (429 or a
        deferred job claim). A non-empty `tenant` also charges the shed to
        that tenant's goodput ledger (per-tenant 429 visibility)."""
        if self._pool is not None:
            self._pool.note_shed(n)
        if tenant:
            self._perf.note_tenant_shed(tenant, n)
        in_grace = time.time() < self._compile_grace_until
        self._flight.event("shed", n=n, in_grace=in_grace)
        if in_grace:
            # the post-mortem distinction this PR exists for: work dropped
            # because a compile held the loop, not because of a real wedge
            self._watchdog_transition("shed_in_grace")
        self._anomaly.signal("shed_in_grace", in_grace=in_grace, shed=n)

    def current_tps(self, window_s: float = 10.0) -> float:
        now = time.time()
        with self.stats_lock:
            self._window = [(t, n) for t, n in self._window if now - t <= window_s]
            toks = sum(n for _, n in self._window)
        return toks / window_s

    def slots_in_use(self) -> int:
        return sum(1 for s in self._slots if s is not None) + len(self._prefills)

    def queue_depth(self) -> int:
        """Requests accepted by submit() but not yet admitted to a slot."""
        return self._admit.qsize()

    # -- engine loop -------------------------------------------------------

    def _bucket(self, n: int) -> int:
        # sp prefill shards the bucket over the sp axis — keep it divisible;
        # and on the pallas prefill path every rung must be a legal flash
        # block shape (192 is not: S >= 128 needs S % 128 == 0, sub-128
        # rungs must be pow2 — kernels/attention.py:pallas_supported).
        # Midpoint rungs failing either rule fall back to the pow2 rung.
        if self.prefill_fine:
            b = fine_bucket(n, self.max_seq_len)
            ok_sp = b % max(self.sp, 1) == 0
            ok_impl = self.attn_impl != "pallas" or pallas_supported(
                b, self.cfg.resolved_head_dim
            )
            if ok_sp and ok_impl:
                return max(b, self.sp)
        return max(pow2_bucket(n, self.max_seq_len), self.sp)

    def _recover_cache(self) -> bool:
        """Re-allocate the KV cache if a failed dispatch consumed the donated
        buffers (donate_argnums invalidates inputs even when execution
        raises); without this every later round would see a deleted Array.
        Returns True when a re-allocation happened (all slot KV was lost)."""
        try:
            leaves = jax.tree.leaves(
                {"k": self._ck, "v": self._cv,
                 "p": (self._d_temp, self._d_topk, self._d_topp,
                       self._d_last_tok),
                 "x": ({} if self._pool_k is None
                       else {"k": self._pool_k, "v": self._pool_v})}
            )
            deleted = any(x.is_deleted() for x in leaves)
        except AttributeError:
            deleted = False
        if not deleted:
            return False
        if self._spmd:
            # The poisoned step already fanned out: followers executed (or
            # wedged on) the same dispatch, and freshly-allocated buffers
            # here could never be re-synchronized through replay. The engine
            # goes dead instead — submits reject, the loop exits, followers
            # get the stop command from the loop tail.
            self._mark_dead("kv cache lost in a failed dispatch")
            return True
        # the device sampling rows and token ring are also donated; host
        # mirrors are the source of truth, so rebuilding them is lossless
        # (the ring may lag by the in-flight rounds that were lost — their
        # slots were failed/aborted, so no live stream reads the stale rows)
        self._d_temp = jnp.asarray(self._temp)
        self._d_topk = jnp.asarray(self._topk)
        self._d_topp = jnp.asarray(self._topp)
        self._d_last_tok = jnp.asarray(self._last_tok)
        log.warning("KV cache buffers were donated into a failed dispatch; re-allocating")
        cache = init_kv_cache(
            self.cfg, self.max_slots, self.max_seq_len, dtype=self.dtype,
            quantized=self.kv_quant == "int8",
        )
        if self.mesh is not None:
            cache = shard_pytree(
                cache, kv_cache_specs(quantized=self.kv_quant == "int8",
                               latent=bool(self.cfg.kv_lora_rank)), self.mesh
            )
        self._ck = cache["k"]
        self._cv = cache["v"]
        if self._phys is not None:
            # the physical pools ride the same donation paths (_pool_put_*
            # donate them; _cow_block_fn donates the arena they feed) — any
            # prefix entry's pool bytes are now suspect, so drop them all.
            # _abort_all follows every _recover_cache()=True return and
            # resets the per-slot tables + sweeps the id map.
            self._pool_k = pool_like(self._ck, self._paging.prefix_partition,
                                     self._paging.block_tokens)
            self._pool_v = pool_like(self._cv, self._paging.prefix_partition,
                                     self._paging.block_tokens)
            if self.mesh is not None:
                pspecs = kv_pool_specs(
                    quantized=self.kv_quant == "int8",
                    latent=bool(self.cfg.kv_lora_rank),
                )
                self._pool_k = shard_pytree(self._pool_k, pspecs["k"], self.mesh)
                self._pool_v = shard_pytree(self._pool_v, pspecs["v"], self.mesh)
            while self._prefix_cache:
                self._evict_lru_prefix()
            self._phys.reset_all()
        return True

    def _count_error(self, n: int = 1) -> None:
        """All total_errors bumps go through here: the counter is read as
        deltas by bench.py's degenerate-window gate and written from both the
        engine and watchdog threads, so it must always be under stats_lock."""
        with self.stats_lock:
            self.total_errors += n

    def _note_exec_shape(self, *key) -> bool:
        """Record a dispatch shape; first sighting opens a compile-grace
        window equal to the stall timeout (see __init__). Returns True on
        first sighting — the caller times that dispatch into the compile
        ledger (_compile_obs): jit traces+compiles synchronously inside the
        first call of a shape, so its wall time IS the compile time."""
        if key in self._seen_exec_shapes:
            return False
        self._seen_exec_shapes.add(key)
        now = time.time()
        in_grace = now < self._compile_grace_until
        self._compile_grace_until = max(
            self._compile_grace_until, now + self.stall_timeout_s
        )
        if not in_grace:
            # one transition per grace EPISODE, not per shape — overlapping
            # first sightings extend the same open window
            self._watchdog_transition("compile_grace")
        return True

    def _watchdog_transition(self, state: str) -> None:
        """Count a watchdog/compile-grace state transition and journal it:
        `llmtpu_watchdog_transitions_total{state=...}` + a recorder event,
        so "shed while compiling" is distinguishable from a real wedge in
        post-mortems. Called from the engine loop, the watchdog thread, and
        the API's shed path — hence stats_lock."""
        with self.stats_lock:
            self.watchdog_transitions[state] = (
                self.watchdog_transitions.get(state, 0) + 1
            )
        self._flight.event("watchdog", state=state)

    def _compile_obs(self, phase: str, key: tuple, wall_s: float,
                     src: str = "serve") -> None:
        """First dispatch of an executable shape → compile ledger entry +
        recorder event (the ROADMAP item-5 cold-start measurement).
        `src` is provenance: "serve" for real dispatches, "warmup" for the
        planner's AOT compiles — /v1/debug/compiles shows whether the
        serve path ever ate a cold compile warmup should have absorbed."""
        ks = ":".join(str(p) for p in key)
        e = self._ledger.observe(phase, ks, wall_s, src=src)
        self._flight.event(
            "compile", phase=phase, key=ks,
            wall_ms=round(wall_s * 1e3, 1), hit=e["hit"],
        )

    def _paging_event(self, ops: list[tuple]) -> None:
        """Paged-ledger observer (paging.py on_ops): sharing-relevant block
        ops → flight events. Runs under the rank-30 paging lock, so it only
        performs lock-free recorder appends."""
        for op in ops:
            kind = op[0]
            if kind == "pin":
                self._flight.event("pin", slot=op[1], blocks=len(op[2]))
            elif kind == "cow":
                self._flight.event("cow", slot=op[1], src=op[2], dst=op[3])
            elif kind == "free":
                self._flight.event("unpin", slot=op[1], blocks=len(op[2]))
            elif kind == "snap":
                self._flight.event(
                    "snap", snap_id=op[1], slot=op[2],
                    shared=len(op[3]), private=len(op[4]),
                )

    # -- physical paged KV (block tables + prefix pool, physical.py) -------

    def _phys_reset(self, slot: int) -> None:
        """Slot released (free/preempt): its table row back to identity,
        then reclaim pool rows whose ledger ids just died. Driven from the
        mutator call sites, never from on_ops — the observer runs under the
        paging lock and sweep/table_view re-take it."""
        if self._phys is None:
            return
        if self._phys.reset(slot):
            self._flight.event("pg_tbl", slot=slot, action="reset")
        self._phys.sweep(self._paging.alive)

    def _phys_sweep(self) -> None:
        """Reclaim pool rows after a pin-dropping mutation that re-keys no
        table (drop_snap, prefix_release outside the eviction path)."""
        if self._phys is not None:
            self._phys.sweep(self._paging.alive)

    def _phys_rebuild(self, slot: int) -> None:
        """Re-key one slot's device table row from the ledger's view (after
        pin / restore mutations)."""
        if self._phys is None:
            return
        ids, sn = self._paging.table_view(slot)
        if self._phys.rebuild(slot, ids, sn):
            self._flight.event("pg_tbl", slot=slot, action="rebuild", shared=sn)

    def _phys_admit(self, slot: int, ent: dict, ops: list[tuple]) -> None:
        """Physical side of a shared admission (prefix hit or migrated-in
        re-pin): execute the ledger's COW op as ONE whole-block device copy
        out of the entry's pool row, then rebuild the slot's table row.
        Exactly one boundary block ever copies — aligned stored lengths
        copy nothing at all."""
        if self._phys is None:
            return
        for op in ops:
            if op[0] != "cow":
                continue
            prow = self._phys.phys_of(op[2])
            if prow is None:  # tripwire: unmapped entry block (audited)
                self._phys.missing_pins += 1
                continue
            blk = int(ent["P"]) // self._paging.block_tokens
            first = self._note_exec_shape("cow")
            t0 = time.perf_counter()
            self._dx("cow", int(slot), int(blk), int(prow - self._phys.pool_base))
            if first:
                self._compile_obs("cow", (self._paging.block_tokens,),
                                  time.perf_counter() - t0)
            self._phys.cow_copies_total += 1
            self._flight.event("pg_cow", slot=slot, blk=blk,
                               pool_row=prow - self._phys.pool_base)
        self._phys_rebuild(slot)
        self._phys_note_hbm()

    def _phys_note_hbm(self) -> None:
        """Sample the honest HBM ledger at a shared admission: what the
        live working set physically occupies (unique blocks — identity
        homes + pool rows, each resident ONCE) against what the
        pre-physical contiguous engine held for the same set (every
        sharer's full row copy, plus the prefix entries' own device rows).
        The peak ratio is bench.py's `paged_hbm_bytes_ratio` line-of-record
        metric; perf_gate floors it."""
        st = self._paging.stats()
        bb = float(self._paging.bytes_per_block)
        used = st["blocks_used"]
        if bb <= 0 or used <= 0:
            return
        phys = used * bb
        contig = st["logical_blocks"] * bb + float(self._prefix_cache_bytes)
        ratio = contig / phys
        if ratio > self._phys_hbm_peak_ratio:
            self._phys_hbm_peak_ratio = ratio
            self._phys_hbm_peak = (contig, phys)

    def _store_prefix_physical(self, slot: int, key: tuple, p0: int) -> bool:
        """Copy a freshly-registered prefix entry's blocks [0, p0) into the
        prefix pool, gathered through the STORING slot's own table (a sharer
        storing a longer prefix reads its shared blocks from the pool, not
        its stale arena rows). False → pool rows unavailable; the caller
        already holds the ledger registration and must release it."""
        ids = self._paging.prefix_ids(key)
        if ids is None:
            return False
        rows = self._phys.register_prefix(ids)
        if rows is None:
            return False
        srcs = self._phys.row_sources(slot, len(ids))
        for j, prow in enumerate(rows):
            in_arena, src_row, off = srcs[j]
            first = self._note_exec_shape("pool_put", in_arena)
            t0 = time.perf_counter()
            if in_arena:
                self._dx("pput", "arena", int(src_row), int(off), int(prow))
            else:
                self._dx("pput", "pool", int(src_row), 0, int(prow))
            if first:
                self._compile_obs("pool_put", (in_arena,),
                                  time.perf_counter() - t0)
        return True

    @staticmethod
    def _tid(req: "GenRequest") -> str:
        """Request's 32-hex trace id for recorder events — a flight dump
        stitches into /v1/traces through it ("" when the request arrived
        without trace context)."""
        ids = tracing.parse_traceparent(req.trace_ctx)
        return ids[0] if ids else ""

    def check_anomalies(self) -> None:
        """Feed the poll-style anomaly detectors (decode-cadence stall,
        paged-leak growth). Read-only over host state, so safe from any
        thread; called by the watchdog loop and engines_info refreshes.
        Event-style detectors (TTFT burn, spec collapse, ping-pong,
        shed-in-grace) are fed at their hot-path sites instead."""
        now = time.time()
        if now >= self._compile_grace_until:
            # inside grace a first-time shape may legitimately be compiling
            # for minutes — cadence gaps there are not stalls
            busy = sum(1 for s in self._slots if s is not None)
            self._anomaly.signal(
                "decode_stall",
                gap_s=now - self._last_round_ts,
                ema_s=self._sched.decode_round_s,
                busy=busy,
            )
        self._anomaly.signal("paged_leak", leak_count=self._paging.leak_count())

    def flight_stats(self) -> dict[str, Any]:
        """Flight-recorder observability block (engines_info + dashboard):
        ring health, anomaly dump counts, watchdog transition counts, and
        the compile ledger's summary."""
        rec = self._flight.stats()
        with self.stats_lock:
            transitions = dict(self.watchdog_transitions)
        return {
            "enabled": 1.0 if self._flight.enabled else 0.0,
            "events_total": float(rec["events_total"]),
            "dropped_events": float(rec["dropped_events"]),
            "dumps": float(rec["dumps"]),
            "last_dump_path": rec["last_dump_path"],
            "anomaly": self._anomaly.stats(),
            "watchdog_transitions": transitions,
            "compile": self._ledger.stats(),
        }

    def anomaly_history(self, limit: int = 20) -> list[dict[str, Any]]:
        return self._anomaly.history(limit)

    def perf_stats(self) -> dict[str, Any]:
        """Perf-observatory block (/v1/debug/perf + engines_info + bench):
        ITL percentiles, goodput split, sampled per-phase host/device/wait
        attribution, and the four-layout roofline. Read-only over the
        observatory's own lock, so safe from any thread."""
        return self._perf.stats()

    def drain_itl_samples(self) -> list[float]:
        """ITL samples (seconds) since the last drain — engines_info feeds
        them to the llmtpu_itl_seconds histogram exactly once."""
        return self._perf.drain_itl()

    def waterfall_stats(self) -> dict[str, Any]:
        """Latency-waterfall block (/v1/debug/latency + engines_info):
        per-stage percentiles, cumulative stage seconds (the
        llmtpu_latency_stage_seconds delta bridge reads these), and the
        stage-coverage ratio. Lock-guarded inside, safe from any thread."""
        return self._waterfall.stats()

    def waterfall_recent(self, limit: int = 32) -> list[dict[str, Any]]:
        """Most recent per-request waterfall rows (newest last)."""
        return self._waterfall.recent(limit)

    def workload_stats(self) -> dict[str, Any]:
        """Workload-capture block: the process-shared ring's health."""
        return self._workload.stats()

    # -- on-demand profiler capture (/v1/debug/profile) --------------------

    def start_profile(self, steps: int, trace_dir: str = "") -> dict[str, Any]:
        """Arm a jax.profiler capture for the next `steps` engine-loop
        iterations. Callable from any thread (API handler, anomaly dump
        callback); the engine thread performs the actual start/stop so the
        capture brackets real device work. Idempotent while one is armed
        or running."""
        steps = max(1, int(steps))
        d = trace_dir or os.environ.get("TPU_FLIGHT_PROFILE_DIR") or os.path.join(
            tempfile.gettempdir(), "llmtpu-profile"
        )
        if self._profile_left > 0 or self._profile_pending is not None:
            return self.profile_status()
        self._profile_pending = (steps, d)
        self._wake.set()
        return self.profile_status()

    def profile_status(self) -> dict[str, Any]:
        pending = self._profile_pending
        return {
            "active": self._profile_left > 0,
            "steps_left": int(self._profile_left),
            "pending_steps": int(pending[0]) if pending else 0,
            "trace_dir": self._profile_dir or (pending[1] if pending else ""),
        }

    def _profile_tick(self) -> None:
        """Engine-thread-only: start a pending capture, count down a live
        one, stop at zero. jax.profiler failures (unsupported backend, dir
        permissions) disarm quietly — profiling must never take the serve
        loop down."""
        if self._profile_pending is not None:
            steps, d = self._profile_pending
            self._profile_pending = None
            try:
                os.makedirs(d, exist_ok=True)
                jax.profiler.start_trace(d)
            except Exception:
                log.exception("jax.profiler start failed; capture disarmed")
                return
            self._profile_left = steps
            self._profile_dir = d
            self._flight.event("profile", action="start", steps=steps, dir=d)
            log.info("profiler capture started: %d steps -> %s", steps, d)
            return
        if self._profile_left > 0:
            self._profile_left -= 1
            if self._profile_left == 0:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    log.exception("jax.profiler stop failed")
                self._flight.event("profile", action="stop", dir=self._profile_dir)
                log.info("profiler capture finished -> %s", self._profile_dir)

    def _abort_all(self, error: str) -> None:
        """Fail every in-flight request — decoding slots AND mid-prefill
        reservations. Called when the KV cache had to be re-allocated: all
        per-slot state on device is gone."""
        for i, s in enumerate(self._slots):
            if s is not None:
                s.aborted = True
                self._count_error()
                s.req.out.put({"type": "error", "error": error})
                s.req.out.put(_DONE)
                self._free_now(i)
        for slot in list(self._prefills):
            st = self._prefills.pop(slot)
            self._paging.free_slot(slot)
            self._phys_reset(slot)
            self._count_error()
            st.req.out.put({"type": "error", "error": error})
            st.req.out.put(_DONE)
        self._prefill_q.clear()
        if self._pool is not None:
            # offloaded snapshots were waiting on a restore that will never
            # come (their KV rows on device are gone with everyone else's)
            for snap in self._pool.drain():
                self._paging.drop_snap(snap.snap_id)
                s = snap.slot_obj
                if s is None or s.aborted or s.done:
                    continue
                s.aborted = True
                self._count_error()
                s.req.out.put({"type": "error", "error": error})
                s.req.out.put(_DONE)
            self._phys_sweep()

    def _free_slot(self, reserved: set[int] | None = None) -> int | None:
        for i, s in enumerate(self._slots):
            if s is None and i not in self._prefills and (
                reserved is None or i not in reserved
            ):
                fence = self._cooling.get(i)
                if fence is not None:
                    if fence > self._rid_fetched:
                        # an in-flight round dispatched before this slot was
                        # freed may still write its cache rows / token ring
                        # entry — reuse only once every such round is fetched
                        continue
                    del self._cooling[i]
                return i
        return None

    # -- KV pool: preemption with host offload -----------------------------

    def _aging_s(self) -> float:
        """Seconds after which a waiter (queue head or offloaded snapshot)
        overrides priority fairness — bounds starvation in both directions."""
        return RESTORE_AGING_TTFT_MULT * self.target_ttft_ms / 1000.0

    def _preempt_wanted(self) -> bool:
        """Should this iteration preempt a slot for the queue head? Only
        when plain admission cannot proceed (no free slot), a victim exists,
        the pool's rate/host-memory guards pass, and the head either
        outranks the lowest-priority active stream or has aged past the
        TTFT deadline (equal-priority load sheds at the API watermark
        instead of thrashing slots here)."""
        pool = self._pool
        if pool is None or self._admit.empty() or not pool.may_preempt():
            return False
        live = [s for s in self._slots if s is not None and not s.done and not s.aborted]
        if not live or self._free_slot() is not None:
            return False
        try:
            # the engine thread is the queue's only consumer, so peeking the
            # head without popping is stable
            head = self._admit.queue[0]
        except IndexError:
            return False
        min_pri = min(s.req.priority for s in live)
        return head.priority > min_pri or (
            time.time() - head.created_at > self._aging_s()
        )

    def _snapshot_rows(self, b: int, Lb: int, start: int = 0):
        """Host copies of slot b's committed KV rows [start, Lb) — one slice
        per cache tree ("q"+"s" for kv8; k/v last dims differ under MLA but
        the seq axis is ALWAYS axis 3, so the same slice covers every
        layout. start > 0 is the paged private-only snapshot: rows [0, start)
        are a shared prefix whose blocks stay pinned in the paging ledger.

        Physical mode: when the snapshot range overlaps the slot's SHARED
        blocks, their arena rows are stale (the bytes live in the prefix
        pool) — resolve block-by-block through the table and concatenate.
        Private blocks are identity homes, so a private-only snapshot
        (start >= shared tokens) keeps the plain contiguous slice."""
        srcs = None
        bt = self._paging.block_tokens
        if self._phys is not None:
            _, sn = self._paging.table_view(b)
            if sn > 0 and start < sn * bt:
                srcs = self._phys.row_sources(b, -(-Lb // bt))
        return self._dx("snap", int(b), int(Lb), int(start), srcs)

    def _preempt_one(self) -> bool:
        """Offload one victim slot to host memory and free it. The caller
        has DRAINED the pipeline (pending emitted, in-flight fetched), so
        the host mirrors are committed-exact: lengths/last_tok describe
        exactly the KV rows on device and the snapshot rolls back to a
        token-identical resume point."""
        pool = self._pool
        # SLO debt (model zoo tenancy): preemption prefers the slot whose
        # tenant is furthest AHEAD of the worst-served tenant's goodput
        # ratio — surplus, not idleness, picks who gives capacity back.
        # With no tenants the ratio map is empty, every surplus is 0.0,
        # and pick_victim's ordering is byte-identical to the pre-zoo
        # policies (true no-op).
        ratios = self._perf.tenant_goodput_ratios()
        floor_ratio = min(ratios.values()) if ratios else 0.0
        cands = []
        for b, s in enumerate(self._slots):
            if s is None or s.done or s.aborted:
                continue
            cands.append({
                "slot": b,
                "priority": s.req.priority,
                "last_activity": s.last_emit or s.first_token_at,
                "tokens_remaining": max(0, s.req.max_tokens - s.generated),
                "slo_surplus": (
                    ratios.get(s.req.tenant, floor_ratio) - floor_ratio
                    if ratios and s.req.tenant else 0.0
                ),
            })
        victim = pool.pick_victim(cands)
        if victim is None:
            return False
        b = victim["slot"]
        s = self._slots[b]
        L = int(self._lengths[b])
        t0 = time.perf_counter()
        Lb = bucket_len(L, self.max_seq_len)
        # Paged private-only offload: a slot admitted off a prefix hit only
        # snapshots rows [shared_len, Lb) — the shared rows' blocks stay
        # pinned (ids, zero bytes) and restore re-inserts them from the
        # entry's device arrays. shared_len < Lb always holds (a hit is a
        # STRICT prefix and both are pow2), but guard anyway.
        p0 = s.shared_len if (0 < s.shared_len < Lb and s.shared_entry) else 0
        if self._phys is not None and p0 % self._paging.block_tokens:
            # an unaligned boundary's COW tokens live ONLY in this slot's
            # arena (the entry keeps no row copies to rebuild them from) and
            # its pool partial-block is NOT pinned by the parked snapshot —
            # park nothing shared, snapshot the whole bucket instead
            p0 = 0
        pool_rows = self._shared_pool_rows(b, p0)
        k_rows, v_rows = self._snapshot_rows(b, Lb, start=p0)
        dt = time.perf_counter() - t0
        snap_id = self._snap_ctr
        self._snap_ctr += 1
        snap = KVSnapshot(
            req_id=s.req.request_id,
            priority=s.req.priority,
            length=L,
            bucket=Lb,
            last_tok=int(self._last_tok[b]),
            temperature=float(self._temp[b]),
            top_k=int(self._topk[b]),
            top_p=float(self._topp[b]),
            k_rows=k_rows,
            v_rows=v_rows,
            nbytes=pytree_nbytes(k_rows) + pytree_nbytes(v_rows),
            preempted_at=time.time(),
            slot_obj=s,
            snap_id=snap_id,
            shared_len=p0,
            shared_entry=s.shared_entry if p0 else None,
            shared_pool_rows=pool_rows,
        )
        pool.offload(snap, dt)
        # ledger: park the shared pins under snap_id, free the private tail
        # — BEFORE _free_now, whose free_slot would drop the whole table
        self._paging.preempt_slot(b, snap_id)
        # free WITHOUT terminal events: the request is suspended, not dead —
        # its consumer stays blocked in out.get() until restore resumes
        # emission. (Post-drain there are no rounds in flight, so this sets
        # no cooling fence.)
        self._free_now(b)
        if s.req.trace_ctx:
            tracing.get_tracer().record(
                "engine.preempt", snap.preempted_at - dt, snap.preempted_at,
                parent=s.req.trace_ctx,
                attrs={
                    "request_id": s.req.request_id,
                    "slot": b,
                    "kv_tokens": L,
                    "offload_bytes": snap.nbytes,
                    "policy": pool.policy,
                },
            )
        self._flight.event(
            "preempt", trace_id=self._tid(s.req),
            request_id=s.req.request_id[:8], slot=b, kv_tokens=L,
            offload_bytes=snap.nbytes, wall_ms=round(dt * 1e3, 1),
        )
        log.info(
            "preempted slot %d (req %s, %d tokens, %.1f MB) in %.1f ms",
            b, s.req.request_id[:8], L, snap.nbytes / (1 << 20), dt * 1e3,
        )
        return True

    def _restore_pending(self) -> bool:
        """Restore offloaded snapshots into free slots, highest priority /
        longest-preempted first. A queued request of >= priority keeps its
        claim on the next free slot unless the snapshot has aged past the
        TTFT deadline (the mirror of _preempt_wanted's fairness rule)."""
        pool = self._pool
        restored = False
        while pool.has_preempted():
            snap = pool.pop_restore()
            if snap is None:
                break
            s = snap.slot_obj
            if s is None or s.done or s.aborted:
                # terminal events already delivered; drop the rows and the
                # ledger's parked shared pins
                self._paging.drop_snap(snap.snap_id)
                self._phys_sweep()
                continue
            aged = time.time() - snap.preempted_at > self._aging_s()
            head = None
            try:
                head = self._admit.queue[0]
            except IndexError:
                pass
            if head is not None and head.priority >= snap.priority and not aged:
                pool.requeue(snap)
                break
            slot = self._free_slot()
            if slot is None:
                pool.requeue(snap)
                break
            try:
                self._restore_snapshot(slot, snap)
            except Exception as e:
                log.exception("restore of preempted slot failed")
                # contiguous path: the ledger still parks this snap's pins
                # (restore_slot runs only after the device inserts succeed)
                # — release them. Physical path: the pins may already be
                # re-tabled (it pins BEFORE the inserts so the boundary COW
                # lands first) — free the half-built table too.
                self._paging.free_slot(slot)
                self._phys_reset(slot)
                self._paging.drop_snap(snap.snap_id)
                self._phys_sweep()
                s.aborted = True
                self._count_error()
                s.req.out.put({"type": "error", "error": str(e)})
                s.req.out.put(_DONE)
                if self._recover_cache():
                    self._abort_all("kv cache lost in failed restore")
                break
            restored = True
        return restored

    def _restore_snapshot(self, b: int, snap: KVSnapshot) -> None:
        """device_put the snapshot's rows and re-activate its slot. Writing
        the full pow2 bucket is exact: rows in [length, bucket) are dead by
        the committed-lengths invariant, and the first post-restore decode
        round writes the real token's KV at position `length` before any
        read attends there."""
        s = snap.slot_obj
        # latency waterfall: wall spent parked off-slot is its own stage,
        # not decode (clamped into the partition at finish)
        s.preempted_s += max(0.0, time.time() - snap.preempted_at)
        t0 = time.perf_counter()
        ledgered = False
        if snap.shared_len and snap.shared_entry is not None:
            # Paged two-stage restore, private rows at start=shared_len. R
            # is exact, never padded (insert_at_fn docstring: padding would
            # clamp the start). The shared prefix comes back two ways:
            # contiguous entries re-insert their device row copies; PHYSICAL
            # entries re-pin — the rebuilt table row resolves the shared
            # blocks into the prefix pool, zero rows move.
            ent = snap.shared_entry
            if "k" in ent:
                first = self._note_exec_shape("restore", snap.shared_len)
                eid = ent.get("eid")
                if eid is None:  # entry predates the plane (raw test pokes)
                    self._eid_ctr += 1
                    eid = ent["eid"] = self._eid_ctr
                    self._x_prefix[eid] = (ent["k"], ent["v"])
                self._dx("insert", eid, np.asarray([b], dtype=np.int32), 1)
            else:
                # ledger pins FIRST: a migrated-in adopt with an unaligned
                # stored length redoes the boundary COW out of the entry's
                # pool row here, and the private insert below then overwrites
                # that block's tail from snap.k_rows — order matters
                if snap.migrated and snap.shared_key is not None:
                    ops = self._paging.admit_shared(
                        b, snap.shared_key, snap.length
                    )
                else:
                    ops = self._paging.restore_slot(
                        b, snap.snap_id, snap.length
                    )
                self._phys_admit(b, ent, ops)
                ledgered = True
                first = self._note_exec_shape("restore", snap.shared_len)
            R = snap.bucket - snap.shared_len
            first = self._note_exec_shape("restore_at", R) or first
            self._dx(
                "insat", snap.k_rows, snap.v_rows, int(b),
                int(snap.shared_len),
            )
        else:
            # one executable per (bucket, group=1) — same cache as prefix-hit
            # admission, so a restore compiles nothing the serve loop hasn't
            first = self._note_exec_shape("restore", snap.bucket)
            self._dx(
                "insrows", snap.k_rows, snap.v_rows,
                np.asarray([b], dtype=np.int32), 1,
            )
        # device sampling rows + token ring, then host mirrors (the source
        # of truth for recovery), then the table entry
        self._dx(
            "samprow", int(b), float(snap.temperature), int(snap.top_k),
            float(snap.top_p), int(snap.last_tok),
        )
        self._lengths[b] = snap.length
        self._last_tok[b] = snap.last_tok
        self._temp[b] = snap.temperature
        self._topk[b] = snap.top_k
        self._topp[b] = snap.top_p
        self._slots[b] = s
        # ledger: re-table the parked shared pins + a fresh private tail.
        # A MIGRATED snapshot has no parked pins on this engine — when its
        # shared-prefix key matched our own cache, the blocks pin through
        # the ordinary admit_shared path instead, the same refcount++ a
        # local prefix hit performs (re-pin, never copy).
        if not ledgered:
            if snap.migrated and snap.shared_len and snap.shared_key is not None:
                self._paging.admit_shared(b, snap.shared_key, snap.length)
            else:
                self._paging.restore_slot(b, snap.snap_id, snap.length)
            # a whole-bucket physical restore still re-pins parked shared
            # blocks (forced-unaligned preempts park them) — re-key the row
            self._phys_rebuild(b)
        dt = time.perf_counter() - t0
        if first:
            self._compile_obs(
                "restore", (snap.bucket, snap.shared_len), dt
            )
        if self._pool is not None and not snap.migrated:
            self._pool.note_restored(snap, dt)
        self._flight.event(
            "migrate_in" if snap.migrated else "restore",
            trace_id=self._tid(s.req), request_id=s.req.request_id[:8],
            slot=b, kv_tokens=snap.length, wall_ms=round(dt * 1e3, 1),
        )
        if s.req.trace_ctx:
            now = time.time()
            tracing.get_tracer().record(
                "engine.migrate_in" if snap.migrated else "engine.restore",
                now - dt, now,
                parent=s.req.trace_ctx,
                attrs={
                    "request_id": s.req.request_id,
                    "slot": b,
                    "kv_tokens": snap.length,
                    "preempted_s": round(now - snap.preempted_at, 3),
                    **({"bytes": snap.nbytes} if snap.migrated else {}),
                },
            )
        log.info(
            "restored req %s into slot %d (%d tokens) after %.1f s off-device",
            s.req.request_id[:8], b, snap.length,
            time.time() - snap.preempted_at,
        )

    # -- KV migration: engine-to-engine transfer (migration.py) ------------

    def _host_tree(self, x):
        """Host copy of a cache subtree — dict-aware ({} is the fused int8
        layout's live sentinel, not absence)."""
        if isinstance(x, dict):
            if not x:
                return {}
            return {k: jax.device_get(v) for k, v in x.items()}
        return jax.device_get(x)

    def _shared_pool_rows(self, b: int, p0: int) -> list[int] | None:
        """Pool-row indices backing slot b's shared blocks [0, p0) — read
        from the live table BEFORE preempt/export frees it (the prefix
        entry itself may be LRU-evicted later, taking its id list with it
        while sharer pins keep the rows alive)."""
        if self._phys is None or p0 <= 0:
            return None
        bt = self._paging.block_tokens
        srcs = self._phys.row_sources(b, p0 // bt)
        if any(in_arena for in_arena, _, _ in srcs):
            self._phys.missing_pins += 1  # tripwire: shared block not pooled
            return None
        return [row for _, row, _ in srcs]

    def _wire_item(self, snap: KVSnapshot, source: str) -> dict[str, Any]:
        """Serialize a host-side snapshot into an outbox item. When the
        snapshot is paged private-only, the shared prefix ships as its
        token KEY (the destination re-pins matching blocks out of its own
        prefix cache via admit_shared) plus the entry's rows as a fallback
        for destinations that never saw the prefix. Records the
        engine.migrate_out span + counters."""
        s = snap.slot_obj
        req = s.req
        t0 = time.perf_counter()
        shared_k = shared_v = None
        if snap.shared_len and snap.shared_entry is not None:
            key = snap.shared_entry.get("key")
            if key is None:
                # entry predates the ledger (tests poke entries in raw):
                # fold into a whole-bucket snapshot, nothing to re-pin
                snap.k_rows = migration.merge_shared_rows(
                    self._host_tree(snap.shared_entry["k"]), snap.k_rows
                )
                snap.v_rows = migration.merge_shared_rows(
                    self._host_tree(snap.shared_entry["v"]), snap.v_rows
                )
                snap.shared_len = 0
            elif "k" in snap.shared_entry:
                snap.shared_key = key
                if snap.shared_entry.get("eid") is not None:
                    shared_k, shared_v = self._dx(
                        "pfxexp", snap.shared_entry["eid"]
                    )
                else:  # entry predates the plane (raw test pokes)
                    shared_k = self._host_tree(snap.shared_entry["k"])
                    shared_v = self._host_tree(snap.shared_entry["v"])
            elif snap.shared_pool_rows is not None:
                # PHYSICAL entry: no device row copies exist — the fallback
                # rows gather from the prefix-pool rows captured at snapshot
                # time (still alive: the parked pins / exporting table hold
                # their ledger ids)
                snap.shared_key = key
                shared_k, shared_v = self._dx(
                    "poolexp", list(snap.shared_pool_rows), snap.shared_len
                )
            else:
                # tripwire: physical entry with no resolvable pool rows —
                # ship the key alone; only a destination with a matching
                # cache entry can adopt (others fail the restore cleanly)
                snap.shared_key = key
        header = migration.snapshot_header(snap, req, s)
        payload = migration.encode_payload(
            header,
            {"k": snap.k_rows, "v": snap.v_rows,
             "shared_k": shared_k, "shared_v": shared_v},
        )
        dt = time.perf_counter() - t0
        with self.stats_lock:
            self.migrated_out_total += 1
            self.migrate_out_bytes_total += len(payload)
        self._flight.event(
            "migrate_out", trace_id=self._tid(req),
            request_id=req.request_id[:8], kv_tokens=snap.length,
            wire_bytes=len(payload), source=source,
        )
        if req.trace_ctx:
            now = time.time()
            tracing.get_tracer().record(
                "engine.migrate_out", now - dt, now,
                parent=req.trace_ctx,
                attrs={
                    "request_id": req.request_id,
                    "kv_tokens": snap.length,
                    "bytes": len(payload),
                    "source": source,
                },
            )
        log.info(
            "migrate-out %s: %d tokens, %.1f KB (%s) in %.1f ms",
            req.request_id[:8], snap.length, len(payload) / 1024, source, dt * 1e3,
        )
        return {"payload": payload, "out": req.out, "req_id": req.request_id}

    def _migrate_export_slot(self, b: int, s: _Slot) -> None:
        """Disaggregated-mode export, engine thread, straight after
        activation: the slot's rows [0, P) are committed (the activating
        dispatch was fetched) and no in-flight round touches this slot (it
        was not active when any was dispatched), so the snapshot is
        committed-exact by the same argument as a drained preempt. The
        first token was already emitted from the prefill logits here; the
        destination resumes at position `length` with `last_tok`."""
        L = int(self._lengths[b])
        Lb = bucket_len(L, self.max_seq_len)
        p0 = s.shared_len if (0 < s.shared_len < Lb and s.shared_entry) else 0
        if self._phys is not None and p0 % self._paging.block_tokens:
            p0 = 0  # same unaligned-boundary rule as _preempt_one
        pool_rows = self._shared_pool_rows(b, p0)
        k_rows, v_rows = self._snapshot_rows(b, Lb, start=p0)
        snap = KVSnapshot(
            req_id=s.req.request_id,
            priority=s.req.priority,
            length=L,
            bucket=Lb,
            last_tok=int(self._last_tok[b]),
            temperature=float(self._temp[b]),
            top_k=int(self._topk[b]),
            top_p=float(self._topp[b]),
            k_rows=k_rows,
            v_rows=v_rows,
            nbytes=pytree_nbytes(k_rows) + pytree_nbytes(v_rows),
            preempted_at=time.time(),
            slot_obj=s,
            shared_len=p0,
            shared_entry=s.shared_entry if p0 else None,
            shared_pool_rows=pool_rows,
        )
        item = self._wire_item(snap, source="prefill")
        # free WITHOUT terminal events: the request is handed off, not dead
        # — its consumer stays blocked in out.get() until the destination
        # resumes emission into the same queue
        self._free_now(b)
        self._migrate_outbox.put(item)

    def migrate_export_one(self) -> dict[str, Any] | None:
        """Coordinator-thread drain hook: pop one offloaded snapshot from
        the pool and serialize it for transfer. The snapshot's rows already
        live on host (the preempt path device_get them), so no engine-loop
        coordination is needed — pool pops are atomic, and a parked slot is
        touched by nobody until whoever popped its snapshot restores it."""
        if self._migrate_outbox is None or self._pool is None:
            return None
        snap = self._pool.pop_restore()
        if snap is None:
            return None
        s = snap.slot_obj
        if s is None or s.done or s.aborted:
            # terminal events already delivered — drop rows + parked pins
            self._paging.drop_snap(snap.snap_id)
            self._phys_sweep()
            return None
        item = self._wire_item(snap, source="pool")
        # the rows (shared fallback included) ride the wire: release the
        # parked shared pins this engine was holding for the restore that
        # will now happen elsewhere
        self._paging.drop_snap(snap.snap_id)
        self._phys_sweep()
        return item

    def migrate_steal_queued(self) -> GenRequest | None:
        """Coordinator-thread drain hook: pop the oldest queued-but-not-
        admitted request (the one stuck longest behind the long tail). It
        holds no KV — re-homing it is a plain submit on the idle engine,
        with the consumer queue riding along on the request object."""
        if self._migrate_outbox is None:
            return None
        try:
            return self._admit.get_nowait()
        except queue.Empty:
            return None

    def migrate_import(self, payload: bytes, out: "queue.Queue[Any] | None" = None) -> GenRequest:
        """Decode a wire payload and queue its snapshot for restore on the
        engine loop. `out` re-homes an existing consumer queue (local
        transport: the source engine's request keeps streaming from the
        same queue object); None creates a fresh one (transfer RPC: the
        service pumps it back over the response stream). Returns the
        reconstructed request. Raises when migration is off or the payload
        cannot run here — callers error the original consumer."""
        if self._migrate_in is None:
            raise RuntimeError("KV migration disabled (TPU_MIGRATE=0)")
        if self._stop_evt.is_set() or self.stalled:
            raise RuntimeError("engine unavailable for migrate-in")
        header, snap = migration.wire_to_snapshot(payload)
        if snap.bucket > self.max_seq_len:
            raise ValueError(
                f"snapshot bucket {snap.bucket} exceeds destination "
                f"max_seq_len {self.max_seq_len}"
            )
        req = GenRequest(
            prompt_ids=[int(t) for t in header["prompt_ids"]],
            max_tokens=int(header["max_tokens"]),
            temperature=snap.temperature,
            top_k=snap.top_k,
            top_p=snap.top_p,
            stop=list(header.get("stop") or []),
            priority=snap.priority,
            request_id=snap.req_id,
            created_at=float(header.get("created_at") or time.time()),
            trace_ctx=header.get("trace_ctx") or "",
            migrations=int(header.get("migrations") or 0) + 1,
            constraint=header.get("constraint"),
            logit_bias=header.get("logit_bias"),
        )
        if out is not None:
            req.out = out
        now = time.time()
        s = _Slot(
            req=req,
            generated=int(header.get("generated") or 0),
            text=header.get("text") or "",
            pending=base64.b64decode(header.get("pending_b64") or ""),
            prompt_len=int(header.get("prompt_len") or len(req.prompt_ids)),
            first_token_at=now,
            last_emit=now,
        )
        snap.slot_obj = s
        # each import is one hop for this request — the ping-pong detector
        # fires when the drain policy shuttles the same KV back and forth
        self._anomaly.signal("migration_pingpong", request_id=req.request_id)
        self._migrate_in.put((snap, header, len(payload), s))
        self._wake.set()
        return req

    def migrate_import_stream(self, payload: bytes) -> Iterator[dict[str, Any]]:
        """Transfer-RPC adapter: import, then yield the resumed request's
        events until terminal — the service streams them back to the source
        host, which pumps them into the original consumer queue."""
        req = self.migrate_import(payload)
        while True:
            evt = req.out.get()
            if evt is _DONE:
                return
            yield evt
            if evt.get("type") == "done":
                return

    def _migrate_restore_pending(self) -> bool:
        """Engine thread: restore migrated-in snapshots into free slots.
        Peek-then-pop — the engine thread is the inbox's only consumer, so
        an item stays queued (not requeued) while no slot is free."""
        restored = False
        while not self._migrate_in.empty():
            slot = self._free_slot()
            if slot is None:
                break
            try:
                snap, header, nbytes, s = self._migrate_in.get_nowait()
            except queue.Empty:
                break
            snap.snap_id = self._snap_ctr
            self._snap_ctr += 1
            if snap.shared_len:
                # paged pin handoff: same key at the same stored length in
                # OUR prefix cache → adopt the local entry; its blocks
                # re-pin (refcount++) through admit_shared in
                # _restore_snapshot instead of copying rows. Otherwise fold
                # the shipped fallback rows into a whole-bucket restore.
                ent = (
                    self._prefix_cache.get(snap.shared_key)
                    if snap.shared_key is not None
                    else None
                )
                if ent is not None and int(ent["P"]) == snap.shared_len:
                    snap.shared_entry = ent
                    self._prefix_cache.move_to_end(snap.shared_key)
                else:
                    try:
                        migration.flatten_to_whole_bucket(snap)
                    except ValueError as e:
                        self._count_error()
                        s.req.out.put({"type": "error", "error": str(e)})
                        s.req.out.put(_DONE)
                        continue
            if self._constrain is not None and (
                header.get("constraint") or header.get("logit_bias")
            ):
                # rebuild the automaton cursor HERE (engine thread — the
                # compile cache is not locked) and replay the consumed ids
                # so masking resumes mid-constraint on this host
                try:
                    s.req.cn = self._constrain.make(
                        header.get("constraint"), header.get("logit_bias")
                    )
                except constrain.GrammarError as e:
                    self._count_error()
                    s.req.out.put(
                        {"type": "error", "error": f"constraint: {e}"}
                    )
                    s.req.out.put(_DONE)
                    continue
                self.cn_requests += 1
                s.req.cn.replay(
                    [int(t) for t in header.get("cn_tokens") or []]
                )
                s.cn = s.req.cn
            try:
                self._restore_snapshot(slot, snap)
            except Exception as e:
                log.exception("migrate-in restore failed")
                self._paging.free_slot(slot)
                self._phys_reset(slot)
                self._paging.drop_snap(snap.snap_id)
                self._phys_sweep()
                s.aborted = True
                self._count_error()
                s.req.out.put({"type": "error", "error": str(e)})
                s.req.out.put(_DONE)
                if self._recover_cache():
                    self._abort_all("kv cache lost in failed migrate-in")
                break
            with self.stats_lock:
                self.migrated_in_total += 1
                self.migrate_in_bytes_total += int(nbytes)
            restored = True
        return restored

    def migration_stats(self) -> dict[str, float]:
        """Cumulative migration counters for engines_info/dashboard —
        {"enabled": 0.0} when TPU_MIGRATE is off (mirrors memory_stats)."""
        if self._migrate_outbox is None:
            return {"enabled": 0.0}
        with self.stats_lock:
            return {
                "enabled": 1.0,
                "migrated_out_total": float(self.migrated_out_total),
                "migrated_in_total": float(self.migrated_in_total),
                "migrate_out_bytes_total": float(self.migrate_out_bytes_total),
                "migrate_in_bytes_total": float(self.migrate_in_bytes_total),
                "outbox_depth": float(self._migrate_outbox.qsize()),
                "inbox_depth": float(self._migrate_in.qsize()),
            }

    def _run(self) -> None:
        """Pipelined decode loop (depth 1): the next decode round is DISPATCHED
        before the previous round's tokens are emitted, so host-side work —
        token emission (tokenizer + queue puts, the dominant host cost at
        8B B=80), admissions, prefill dispatches — overlaps the device
        compute instead of serializing with it (measured: the serialized
        loop idled the chip down to ~2.0k tok/s against a 4.8k raw decode
        loop; the reference never faces this — Ollama owns its hot loop).

        Order within one iteration:
          1. stage a prefill chunk group under the token-budget scheduler's
             budget (scheduler.py — bounded so the group costs ~one decode
             round of device time)
          2. dispatch round N FUSED with the staged group (fused_step_fn:
             decode never stalls behind prefill; with no active decode rows
             the group runs standalone, back-to-back); advance chunk
             progress and activate finished prompts
          3. emit round N-1's tokens + admissions (overlapped with 2's
             device time)
          4. fetch round N; fast finish-scan frees finishing slots and
             advances host mirrors (emission itself is deferred to the next
             iteration's step 3)
        """
        pending: _PendingRound | None = None
        inflight: deque[_DispatchedRound] = deque()
        K = self.decode_chunk
        S = self.max_seq_len
        # wall-clock budget per loop phase (serve breakdown, bench.py):
        # where an engine-loop second actually goes — the published answer
        # to "why is serve below raw decode"
        phase = self._phase_s

        def timed(key, fn, *a, **kw):
            t0 = time.perf_counter()
            try:
                return fn(*a, **kw)
            finally:
                phase[key] += time.perf_counter() - t0

        def drain_failed(e: Exception, also: list[int] = ()) -> None:
            # a poisoned round invalidates every LATER in-flight round too
            # (they consumed the same donated buffer chain): fail all of
            # their live slots — plus `also` (the active set of a dispatch
            # that raised BEFORE entering the deque: without it those slots
            # would stay active, re-dispatch, and re-raise forever while
            # their consumers hang) — drop the rounds, recover the cache
            slots: set[int] = {b for b in also if self._slots[b] is not None}
            while inflight:
                d = inflight.popleft()
                slots.update(
                    b for b, s, _ in d.entries if self._slots[b] is s
                )
            self._rid_fetched = self._rid_dispatched  # nothing left in flight
            self._fail_round(sorted(slots), e)

        while not self._stop_evt.is_set():
            # watchdog stamp: idle loops iterate (the _wake wait times out),
            # so staleness only accrues while a device call blocks. A
            # resuming loop clears the stall flag itself — waiting for the
            # watchdog's next poll (up to 30 s) would keep rejecting
            # submits from an engine that is demonstrably serving again.
            self.last_progress = time.time()
            self._backend.idle()  # liveness beacon while the queue is quiet
            if self.stalled:
                self.stalled = False
                self._watchdog_transition("recovered")
                log.warning("engine loop resumed; clearing stall flag")
            if self._profile_pending is not None or self._profile_left > 0:
                self._profile_tick()
            if self._pool is not None and self._preempt_wanted():
                # Preemption needs committed-exact host mirrors: lengths
                # advance optimistically at dispatch and last_tok updates at
                # fetch, so drain the pipeline first (the spec-round drain
                # pattern below) before snapshotting the victim's rows.
                if pending is not None:
                    timed("emit", self._emit_round, pending)
                    pending = None
                ok = True
                while inflight:
                    disp = inflight.popleft()
                    try:
                        fetched = timed("fetch", self._complete_round, disp)
                    except Exception as e:
                        inflight.appendleft(disp)
                        drain_failed(e)
                        ok = False
                        break
                    timed("emit", self._emit_round, fetched)
                if ok and self._preempt_wanted():
                    # re-check: the drain may have finished slots, making a
                    # free slot appear without any eviction
                    self._preempt_one()
            # dispatchable = active rows whose next K writes still fit. Rows
            # at the cap wait (un-dispatched) for their in-flight round's
            # fetch, where the fast-scan cap rule finishes them.
            active = [
                i for i, s in enumerate(self._slots)
                if s is not None and self._lengths[i] + K <= S
            ]
            # Constrained slots leave the pipelined path entirely: their
            # next mask depends on their previous token, so each round is
            # synchronous and committed-exact (_cn_round — masked verify
            # when drafts compose, masked single step otherwise). They are
            # never in `inflight`, so no drain is needed here, and they
            # must never leak into the UNMASKED spec rounds below.
            cn_active = [i for i in active if self._slots[i].cn is not None]
            active = [i for i in active if self._slots[i].cn is None]
            if cn_active:
                try:
                    timed("dispatch", self._cn_round, cn_active)
                except Exception as e:
                    # cn jits donate the cache chain like decode rounds: a
                    # poisoned dispatch invalidates in-flight rounds too
                    if pending is not None:
                        self._emit_round(pending)
                        pending = None
                    drain_failed(e, also=cn_active)
            if self._verify_fn is not None and active:
                if self._spec_cooldown > 0:
                    self._spec_cooldown -= 1
                elif self._stage_spec(active) is not None:
                    # Speculative verify round (majority of active slots have
                    # an n-gram draft). Acceptance is data-dependent, so the
                    # optimistic-length pipelining contract doesn't hold:
                    # drain the in-flight rounds (emitting in round order —
                    # drafts must continue the COMMITTED history) and run the
                    # verify synchronously. Iterations without a draft
                    # majority leave the pipelined path untouched.
                    if pending is not None:
                        timed("emit", self._emit_round, pending)
                        pending = None
                    ok = True
                    while inflight:
                        disp = inflight.popleft()
                        try:
                            fetched = timed("fetch", self._complete_round, disp)
                        except Exception as e:
                            inflight.appendleft(disp)
                            drain_failed(e)
                            ok = False
                            break
                        timed("emit", self._emit_round, fetched)
                    if ok:
                        # re-draft against the post-drain history (slots may
                        # have finished; tokens arrived). Constrained slots
                        # stay filtered out — they already ran their masked
                        # round above and must not join an unmasked verify.
                        active = [
                            i for i, s in enumerate(self._slots)
                            if s is not None and self._lengths[i] + K <= S
                            and s.cn is None
                        ]
                        entries = self._stage_spec(active) if active else None
                        if entries is not None:
                            # verify tokens count against the round's prefill
                            # token budget like prefill chunks (scheduler.py)
                            reserved = sum(1 + len(d) for _, d in entries)
                            group = timed(
                                "prefill", self._stage_prefill_group,
                                len(active), reserved,
                            )
                            try:
                                timed("dispatch", self._spec_round, entries)
                            except Exception as e:
                                if group is not None:
                                    self._fail_prefill_group(group, e)
                                    group = None
                                drain_failed(e, also=active)
                            else:
                                if group is not None:
                                    timed("prefill",
                                          self._dispatch_prefill_group, group)
                            timed("admit", self._admit_pending)
                            continue
            # Token-budget scheduling (see scheduler.py): stage up to
            # `prefill_token_budget` prompt tokens from mid-prefill slots,
            # then FUSE the chunk group into the decode dispatch — decode
            # cadence never stalls behind a prefill backlog, and the group's
            # device time is capped at ~one decode round by construction.
            group = timed("prefill", self._stage_prefill_group, len(active))
            if active:
                try:
                    # tokens come from the device ring, lengths advance
                    # optimistically — this dispatch does NOT wait for any
                    # earlier round's fetch (decode_chunk_fn docstring)
                    inflight.append(
                        timed("dispatch", self._dispatch_decode, active, group)
                    )
                except Exception as e:  # a poisoned dispatch must not kill the loop
                    if pending is not None:
                        # deliver already-fetched tokens BEFORE the error
                        # events — _fail_round marks these same slot objects
                        # aborted, which would silently drop up to K
                        # computed tokens per stream
                        self._emit_round(pending)
                        pending = None
                    if group is not None:
                        self._fail_prefill_group(group, e)
                        group = None
                    drain_failed(e, also=active)
                else:
                    if group is not None:
                        # advance chunk progress + activate finished prompts
                        # (samples from the fused round's prefill logits)
                        timed("prefill", self._finish_prefill_group, group)
            elif group is not None:
                # pure-prefill window: nothing decoding, so the group runs as
                # a standalone chunk dispatch — back-to-back, no wall pacing
                # (the stale-budget alternation this replaces paced cold
                # bursts in arbitrary 50 ms slices)
                timed("prefill", self._dispatch_prefill_group, group)
            if pending is not None:
                timed("emit", self._emit_round, pending)
                pending = None
            admitted = timed("admit", self._admit_pending)
            # fetch the OLDEST round only once the pipeline is full (or the
            # batch went idle): up to pipeline_depth rounds chain on device
            # without a host sync, so a slow tunnel fetch overlaps compute
            # instead of serializing with it
            if inflight and (
                len(inflight) >= self.pipeline_depth or not active
            ):
                disp = inflight.popleft()
                try:
                    pending = timed("fetch", self._complete_round, disp)
                except Exception as e:  # poisoned execution surfaces at fetch
                    inflight.appendleft(disp)  # drain fails its slots too
                    drain_failed(e)
            elif not (active or cn_active or admitted or group is not None
                      or inflight):
                t_idle = time.perf_counter()
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                phase["idle"] += time.perf_counter() - t_idle
        if pending is not None:
            # flush the deferred emission: consumers of slots the fast-scan
            # already freed would otherwise never see their done event
            self._emit_round(pending)
        while inflight:
            # fetch + emit what was still in flight at shutdown: their
            # consumers' streams end cleanly instead of hanging mid-queue
            try:
                self._emit_round(self._complete_round(inflight.popleft()))
            except Exception:  # pragma: no cover — device died at shutdown
                log.exception("in-flight round lost at shutdown")
                break
        if self.dead:
            # dead-on-poison: fail live slots and everything still queued —
            # their consumers must not hang on a loop that will never
            # iterate again
            self._abort_all(f"engine dead: {self.dead}")
            while True:
                try:
                    req = self._admit.get_nowait()
                except queue.Empty:
                    break
                req.out.put(
                    {"type": "error", "error": f"engine dead: {self.dead}"}
                )
                req.out.put(_DONE)
        # release the followers: replay ends exactly where the leader's
        # scheduling loop ends (clean shutdown and dead engine alike)
        self._backend.stop()

    def _fail_round(self, slots: list[int], e: Exception) -> None:
        log.exception("decode round failed; failing %d active slots", len(slots))
        for b in slots:
            s = self._slots[b]
            if s is not None:
                s.aborted = True
                self._count_error()
                s.req.out.put({"type": "error", "error": str(e)})
                s.req.out.put(_DONE)
                self._free_now(b)
        if self._recover_cache():
            # mid-prefill KV lives in the same buffers
            self._abort_all("kv cache lost in failed decode round")

    def _cn_attach(self, req: GenRequest) -> bool:
        """Compile the request's constraint (and/or logit_bias) into the
        per-slot automaton cursor on ``req.cn``. Compilation is host-only
        and LRU-cached by schema hash; a bad spec errors the request here
        (the API already 400s well-formed-but-unsupported specs, this is
        the engine-side backstop). Returns False when the request died."""
        if self._constrain is None or not (req.constraint or req.logit_bias):
            return True
        before = self._constrain.stats_d["misses"]
        t0 = time.perf_counter()
        try:
            req.cn = self._constrain.make(req.constraint, req.logit_bias)
        except constrain.GrammarError as e:
            self._count_error()
            req.out.put({"type": "error", "error": f"constraint: {e}"})
            req.out.put(_DONE)
            return False
        self.cn_requests += 1
        self._flight.event(
            "cn_cmp",
            miss=self._constrain.stats_d["misses"] > before,
            states=req.cn.cc.n_states() if req.cn.cc is not None else 0,
            us=int((time.perf_counter() - t0) * 1e6),
        )
        return True

    def _cn_payload(self, cns: list, n_rows: int):
        """Pack (masks, bias_ids, bias_vals) dispatch operands for a round
        of ``n_rows`` rows where row i serves cursor ``cns[i]`` (None =
        unconstrained). Returns None when nothing is constrained — the op
        closures then call the unmasked executable, so plain traffic never
        traces a masked variant. Pad/unconstrained rows get all-ones masks
        and empty bias (mask-add of 0 over everything = identity)."""
        if not any(cn is not None for cn in cns):
            return None
        t0 = time.perf_counter()
        W = constrain.mask_words(self.cfg.vocab_size)
        NB = self.cn_bias_max
        masks = np.full((n_rows, W), 0xFFFFFFFF, dtype=np.uint32)
        bids = np.full((n_rows, NB), -1, dtype=np.int32)
        bvals = np.zeros((n_rows, NB), dtype=np.float32)
        for i, cn in enumerate(cns):
            if cn is None:
                continue
            masks[i] = cn.mask_row()
            nb = min(len(cn.bias_ids), NB)
            if nb:
                bids[i, :nb] = cn.bias_ids[:nb]
                bvals[i, :nb] = cn.bias_vals[:nb]
        self.cn_mask_s += time.perf_counter() - t0
        return masks, bids, bvals

    def _admit_pending(self) -> bool:
        admitted = False
        if self._migrate_in is not None and not self._migrate_in.empty():
            # migrated-in snapshots re-enter first: their prefill was spent
            # on another engine and their consumers have been waiting since
            admitted = self._migrate_restore_pending() or admitted
        if not self._prefix_rpc_in.empty():
            # parked prefix_fetch work (export gathers / import uploads):
            # serviced here because only the engine thread may touch the
            # prefix cache and dispatch against the device pool
            self._drain_prefix_rpc()
        if self._pool is not None and self._pool.has_preempted():
            # offloaded snapshots re-enter ahead of the queue (subject to
            # the fairness/aging rule inside) — they already spent their
            # prefill and hold committed tokens
            admitted = self._restore_pending() or admitted
        while True:
            batch: list[tuple[int, GenRequest, list[int]]] = []
            # prefix-cache hits grouped by entry: one fused row-copy
            # dispatch serves the whole group
            hits: dict[int, tuple[dict, list]] = {}
            reserved: set[int] = set()
            while len(batch) < self.admit_batch:
                slot = self._free_slot(reserved)
                if slot is None:
                    break
                try:
                    req = self._admit.get_nowait()
                except queue.Empty:
                    break
                req.admitted_at = time.time()
                ids = req.prompt_ids
                # Leave room for at least one decode chunk after the prompt.
                max_prompt = self.max_seq_len - self.decode_chunk
                if len(ids) > max_prompt:  # keep the tail (left-truncation)
                    ids = ids[-max_prompt:]
                if req.max_tokens <= 0:
                    req.out.put(
                        {
                            "type": "done",
                            "finish_reason": "length",
                            "usage": {
                                "prompt_tokens": len(ids),
                                "completion_tokens": 0,
                                "total_tokens": len(ids),
                            },
                            "ttft_ms": 0.0,
                        }
                    )
                    req.out.put(_DONE)
                    continue
                admitted = True
                if not self._cn_attach(req):
                    continue  # bad constraint spec: request already errored
                ent = self._match_prefix(ids)
                if ent is not None:
                    # cached prefix: copy its KV rows, chunk-prefill only
                    # the suffix (works for any suffix length — the chunked
                    # machinery is ragged-safe)
                    reserved.add(slot)
                    hits.setdefault(id(ent), (ent, []))[1].append(
                        (slot, req, list(ids))
                    )
                    continue
                if self.sp == 1 and self.prefill_chunk and len(ids) > self.prefill_chunk:
                    # Long prompt: reserve the slot and prefill chunk-by-chunk
                    # under the token-budget scheduler, fused into decode
                    # rounds (no head-of-line blocking of in-flight streams).
                    # sp>1 keeps whole-prompt prefill: the sp axis bounds
                    # per-chip work.
                    self._prefills[slot] = _PrefillState(req=req, ids=list(ids))
                    self._prefill_q.append(slot)
                    # ledger: reserve the prompt's blocks for the whole
                    # chunked prefill (the rows are written incrementally
                    # but the commitment is made now)
                    self._paging.admit_slot(slot, len(ids))
                    continue
                reserved.add(slot)
                batch.append((slot, req, list(ids)))
            for ent, group in hits.values():
                try:
                    self._start_cached(ent, group)
                except Exception as e:
                    log.exception("prefix-cache admission failed")
                    for slot, req, _ in group:
                        self._prefills.pop(slot, None)
                        self._paging.free_slot(slot)
                        self._phys_reset(slot)
                        try:
                            self._prefill_q.remove(slot)
                        except ValueError:
                            pass
                        self._count_error()
                        req.out.put({"type": "error", "error": str(e)})
                        req.out.put(_DONE)
                    if self._recover_cache():
                        self._abort_all("kv cache lost in failed prefix admission")
            if not batch:
                if hits:
                    continue  # hit slots consumed; more queue may admit
                break
            try:
                self._start_batch(batch)
            except Exception as e:  # malformed batch must not kill the loop
                log.exception("prefill failed")
                for slot, req, _ in batch:
                    # rows activated before the failure hold live slots whose
                    # consumers are about to get the error — free them so the
                    # continuous batch doesn't decode into dead queues
                    s = self._slots[slot]
                    if s is not None and s.req is req:
                        self._free_now(slot)
                    self._count_error()
                    req.out.put({"type": "error", "error": str(e)})
                    req.out.put(_DONE)
                if self._recover_cache():
                    self._abort_all("kv cache lost in failed prefill")
            if len(batch) < self.admit_batch:
                break  # admit queue drained
        return admitted

    # -- prompt-prefix KV cache --------------------------------------------

    PREFIX_MIN = 32  # shortest prefix worth caching (tokens)

    @staticmethod
    def _common_len(a: tuple, b: tuple) -> int:
        n = min(len(a), len(b))
        i = 0
        while i < n and a[i] == b[i]:
            i += 1
        return i

    def _match_prefix(self, ids: list[int]) -> dict | None:
        """Longest cached entry that is a STRICT prefix of `ids` (at least
        one suffix token must remain — the suffix chunk produces the
        first-sample logits)."""
        if not self._prefix_budget or not self._prefix_cache:
            return None
        t = tuple(ids)
        best_key, best = None, None
        # Stored lengths are pow2-floored (_maybe_store_prefix), so the
        # by-length buckets number O(log S): probe longest-first with one
        # hash lookup each instead of scanning every entry and comparing
        # prefix_len tokens per entry (O(entries × prefix_len) at scale).
        for P in sorted(self._prefix_by_len, reverse=True):
            if P >= len(t):
                continue  # strict prefix: >= 1 suffix token must remain
            e = self._prefix_by_len[P].get(t[:P])
            if e is not None:
                best_key, best = t[:P], e
                break
        if best is not None:
            self._prefix_cache.move_to_end(best_key)  # LRU touch
            self.prefix_cache_hits += 1
        else:
            self.prefix_cache_misses += 1
        return best

    def _start_cached(self, ent: dict, group: list) -> None:
        """Admit a group of prefix-cache hits: ONE fused dispatch copies the
        entry's KV rows into every slot; the suffixes then ride the ordinary
        chunked-prefill queue (start=P0) and activate as usual."""
        maybe_fail("engine.prefill", f"prefix-hit slots={[s for s, _, _ in group]}")
        key = ent.get("key")
        if "k" in ent:
            # contiguous entries (physical paging off, or raw test pokes):
            # ONE fused dispatch duplicates the rows into every hit slot
            n = len(group)
            nb = 1 << (n - 1).bit_length()
            slots = np.zeros(nb, dtype=np.int32)
            for i, (slot, _, _) in enumerate(group):
                slots[i] = slot
            eid = ent.get("eid")
            if eid is None:
                # entry predates the dispatch plane (tests poke entries in
                # raw): register its device rows locally so the insert op
                # resolves them — never reachable under a live follower
                self._eid_ctr += 1
                eid = ent["eid"] = self._eid_ctr
                self._x_prefix[eid] = (ent["k"], ent["v"])
            self._dx("insert", eid, slots, n)
        for slot, req, ids in group:
            self._prefills[slot] = _PrefillState(
                req=req, ids=list(ids), done=ent["P"],
                shared_entry=ent, shared_len=ent["P"],
            )
            self._prefill_q.append(slot)
            # ledger: pin the entry's blocks (refcount++, zero allocation
            # for the shared prefix), COW the boundary block if the stored
            # length isn't block-aligned, extend privately to the prompt
            if key is not None:
                ops = self._paging.admit_shared(slot, key, len(ids))
                if "k" not in ent:
                    # PHYSICAL hit admission is pin-only: no row copies at
                    # all — the slot's table row resolves the shared blocks
                    # straight into the prefix pool. Only an unaligned
                    # boundary block copies (once, whole-block, _phys_admit).
                    self._phys_admit(slot, ent, ops)
            else:  # entry predates the ledger (tests poke entries in raw)
                self._paging.admit_slot(slot, len(ids))

    def _maybe_store_prefix(self, slot: int, ids: list[int]) -> None:
        """At activation: if this prompt shares a long prefix with recent
        traffic, store that prefix's KV as a device SLICE of the slot's own
        cache rows (positions [0, P0) hold exactly the prompt KV a cold
        prefill computed — valid for any admission path, batch or chunked,
        and never touched again while the slot decodes at positions >= P)."""
        if not self._prefix_budget:
            return
        t = tuple(ids)
        best = 0
        for other in self._recent_prompts:
            if other is not t:
                best = max(best, self._common_len(t, other))
        # identical prompts cap at len-1: a hit must keep >= 1 suffix
        # token (PREFIX_MIN keeps trivial overlaps out)
        p0 = min(best, len(t) - 1)
        if p0 < self.PREFIX_MIN:
            return
        # pow2-FLOOR the stored length: insert_cached_fn compiles one
        # executable per (entry length, group size) — raw P0 would compile
        # per distinct prefix length on the serve loop (every other jit
        # input shape in this engine is bucketed for exactly this reason).
        # Rounding DOWN stays correct (a shorter prefix is still a prefix).
        p0 = 1 << (p0.bit_length() - 1)
        key = t[:p0]
        if key in self._prefix_cache:
            return
        # Single HBM ledger (paging.py): the entry claims blocks from the
        # manager's prefix partition BEFORE storing — evict LRU entries
        # until it fits; a partition too small for the entry ever skips the
        # store. (The byte counter below stays authoritative too: tests
        # shrink _prefix_budget at runtime and expect byte-LRU eviction.)
        while not self._paging.prefix_can_fit(p0) and self._prefix_cache:
            self._evict_lru_prefix()
        if self._paging.prefix_register(key, p0) is None:
            return
        if self._phys is not None:
            # PHYSICAL store: the entry owns pool rows, not row copies —
            # copy the slot's blocks [0, p0) into the pool (gathered through
            # the slot's own table: a sharer's shared blocks live in the
            # pool already, so those copy pool→pool), and record only the
            # byte ACCOUNTING the LRU budget needs. Every sharer then reads
            # the one pool copy through its block table.
            if not self._store_prefix_physical(slot, key, p0):
                self._paging.prefix_release(key)
                self._phys.sweep(self._paging.alive)
                return
            nbytes = sum(
                (x.size // (x.shape[1] * x.shape[3])) * p0 * x.dtype.itemsize
                for x in jax.tree.leaves((self._ck, self._cv))
            )
            ent = {"P": p0, "bytes": nbytes, "key": key}
            self._prefix_cache[key] = ent
            self._prefix_by_len.setdefault(p0, {})[key] = ent
            self._prefix_cache_bytes += nbytes
            with self._prefix_pub_lock:
                self._prefix_pub[key] = p0
            while self._prefix_cache_bytes > self._prefix_budget and self._prefix_cache:
                self._evict_lru_prefix()
            log.info(
                "prefix cache: stored %d-token prefix in pool (%.1f MB, %d entries)",
                p0, nbytes / 1e6, len(self._prefix_cache),
            )
            return
        self._eid_ctr += 1
        eid = self._eid_ctr
        pk, pv = self._dx("pfxput", eid, int(slot), p0)
        nbytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves((pk, pv)))
        ent = {"P": p0, "k": pk, "v": pv, "bytes": nbytes, "key": key, "eid": eid}
        self._prefix_cache[key] = ent
        self._prefix_by_len.setdefault(p0, {})[key] = ent
        self._prefix_cache_bytes += nbytes
        with self._prefix_pub_lock:
            self._prefix_pub[key] = p0
        while self._prefix_cache_bytes > self._prefix_budget and self._prefix_cache:
            self._evict_lru_prefix()
        log.info(
            "prefix cache: stored %d-token prefix (%.1f MB, %d entries)",
            p0, nbytes / 1e6, len(self._prefix_cache),
        )

    def _evict_lru_prefix(self) -> None:
        """Evict the least-recently-used prefix entry: byte counter, ledger
        registration (blocks stay alive while live tables still pin them),
        and the by-length index."""
        old_key, old = self._prefix_cache.popitem(last=False)
        self._prefix_cache_bytes -= old["bytes"]
        if old.get("eid") is not None:
            self._dx("pfxdrop", old["eid"])
        with self._prefix_pub_lock:
            self._prefix_pub.pop(old_key, None)
        self._paging.prefix_release(old.get("key", old_key))
        if self._phys is not None:
            # pool rows free only once the last sharer pin lets the ledger
            # id die — an evicted entry stays READABLE for its sharers
            self._phys.sweep(self._paging.alive)
        bucket_d = self._prefix_by_len.get(old["P"])
        if bucket_d is not None:
            bucket_d.pop(old_key, None)
            if not bucket_d:
                del self._prefix_by_len[old["P"]]

    # -- fleet prefix tier (prefix-locality routing, remote fetch) ---------

    def prefix_chains(self) -> list[tuple[tuple, int]]:
        """Resident prefix chains as ``(token_key, stored_tokens)`` pairs
        — the digest source. Reads the published mirror, safe from any
        thread."""
        with self._prefix_pub_lock:
            return list(self._prefix_pub.items())

    def prefix_digest(self, top_k: int = prefix_fp.DEFAULT_TOP_K) -> dict | None:
        """Compact digest of resident chains for the discovery tag channel
        (routing/prefix.py build_digest), or None when the prefix cache is
        off or empty — absent tag means "nothing to match", exactly like
        kv_headroom's opt-in semantics."""
        if not self._prefix_budget:
            return None
        chains = self.prefix_chains()
        if not chains:
            return None
        return prefix_fp.build_digest(
            chains, self._paging.block_tokens, top_k=top_k
        )

    def prefix_match_len(self, ids: list[int]) -> int:
        """Longest resident chain that is a STRICT prefix of `ids`
        (thread-safe; the fetch path compares this against a peer's claim
        before paying for the wire)."""
        t = tuple(ids)
        best = 0
        with self._prefix_pub_lock:
            for key, n in self._prefix_pub.items():
                if n > best and n < len(t) and key == t[:n]:
                    best = n
        return best

    def prefix_export(self, ids: list[int], timeout_s: float = 30.0) -> bytes | None:
        """Snapshot the longest resident chain prefixing `ids` as a wire
        payload (the `prefix_fetch` RPC's source side); a chain that only
        partially overlaps ships pow2-truncated to the shared prefix.
        Parks the request on the engine thread — only it may touch the
        prefix cache and the device pool — and blocks the caller until
        served. None on miss, disabled cache, or timeout."""
        if not self._prefix_budget:
            return None
        box: dict[str, Any] = {}
        ev = threading.Event()
        self._prefix_rpc_in.put(("export", (list(ids),), box, ev))
        self._wake.set()
        if not ev.wait(timeout_s):
            return None
        return box.get("payload")

    def prefix_export_by_hash(self, hash16: str, timeout_s: float = 30.0) -> bytes | None:
        """Resolve a digest head hash (routing/prefix.py chain_hashes) back
        to the resident chain's token ids and export it — the boot
        warm-fill path: a joining node learns the fleet's hottest chains
        only as digest hashes from discovery tags, never the ids behind
        them, so the ids must be recovered on the side that HAS them."""
        if not self._prefix_budget:
            return None
        want = str(hash16 or "").strip().lower()
        if not want:
            return None
        bt = self._paging.block_tokens
        with self._prefix_pub_lock:
            chains = list(self._prefix_pub.items())
        for key, n in sorted(chains, key=lambda kv: -kv[1]):
            bounds = prefix_fp.chain_hashes(list(key), bt)
            if bounds and bounds[-1][1] == want:
                return self.prefix_export(list(key), timeout_s=timeout_s)
        return None

    def prefix_import(self, payload: bytes, timeout_s: float = 30.0) -> bool:
        """Adopt a peer's exported prefix chain into the local cache (the
        fetch destination side). Decodes on the caller thread (pure host
        work), then parks the insert on the engine thread. After a
        successful import the next admission sees an ordinary prefix-cache
        hit and re-pins via admit_shared — pin-only, zero row copies on
        the physical path."""
        if not self._prefix_budget:
            return False
        try:
            header, trees = migration.decode_payload(payload)
        except Exception:
            with self.stats_lock:
                self.prefix_import_rejects_total += 1
            return False
        if header.get("kind") != "prefix":
            with self.stats_lock:
                self.prefix_import_rejects_total += 1
            return False
        box: dict[str, Any] = {}
        ev = threading.Event()
        self._prefix_rpc_in.put(("import", (header, trees, len(payload)), box, ev))
        self._wake.set()
        if not ev.wait(timeout_s):
            return False
        return bool(box.get("ok"))

    def _drain_prefix_rpc(self) -> None:
        """Engine thread: service parked prefix export/import requests
        (_admit_pending). Failures report through the box — the waiting
        RPC thread owns error semantics."""
        while True:
            try:
                kind, args, box, ev = self._prefix_rpc_in.get_nowait()
            except queue.Empty:
                return
            try:
                if kind == "export":
                    box["payload"] = self._prefix_export_now(*args)
                else:
                    box["ok"] = self._prefix_import_now(*args)
            except Exception as e:  # noqa: BLE001 — must release the waiter
                log.warning("prefix %s failed: %s", kind, e)
                box["error"] = str(e)
            finally:
                ev.set()

    def _prefix_export_now(self, ids: list[int]) -> bytes | None:
        """Gather the longest resident chain prefixing `ids` into a wire
        payload (engine thread). Non-strict match: exporting the whole
        prompt is fine — the REQUESTER enforces its own strict-prefix rule
        against its (longer) prompt. When no whole chain prefixes the
        request, the best chain ships TRUNCATED to the largest pow2
        prefix both sides share: the advertised digest claims matches at
        block granularity (routing/prefix.py chain hashes), so a peer may
        dial on a partial overlap — refusing it here would waste the RPC
        the router already paid for. Pow2 because import only admits pow2
        lengths (one compiled insert per entry length)."""
        if not self._prefix_cache:
            return None
        t = tuple(ids)
        key, ent, P0 = None, None, 0
        for P in sorted(self._prefix_by_len, reverse=True):
            if P > len(t):
                continue
            e = self._prefix_by_len[P].get(t[:P])
            if e is not None:
                key, ent, P0 = t[:P], e, P
                break
        if ent is None:
            for P, bucket in self._prefix_by_len.items():
                for k2, e in bucket.items():
                    c = self._common_len(k2, t)
                    trunc = 1 << (c.bit_length() - 1) if c else 0
                    if trunc >= self.PREFIX_MIN and trunc < P and trunc > P0:
                        key, ent, P0 = k2, e, trunc
        if ent is None:
            return None
        t0 = time.perf_counter()
        if "k" in ent:
            if ent.get("eid") is not None:
                hk, hv = self._dx("pfxexp", ent["eid"])
            else:  # entry predates the plane (tests poke entries in raw)
                hk, hv = self._host_tree(ent["k"]), self._host_tree(ent["v"])
        else:
            lids = self._paging.prefix_ids(key)
            if lids is None or self._phys is None:
                return None
            rows = []
            for lid in lids[: max(1, P0 // self._paging.block_tokens)]:
                prow = self._phys.phys_of(lid)
                if prow is None:
                    self._phys.missing_pins += 1
                    return None
                rows.append(prow - self._phys.pool_base)
            hk, hv = self._dx("poolexp", rows, P0)
        if P0 < int(ent["P"]) and "k" in ent:
            # contiguous entry: token axis is 3 ([L, 1, H, P, *rest]),
            # dict leaves are the fused-int8 live sentinel
            def _cut(x):
                if isinstance(x, dict):
                    return {k: _cut(v) for k, v in x.items()}
                return x[:, :, :, :P0]

            hk, hv = _cut(hk), _cut(hv)
        header = {
            "kind": "prefix",
            "P": P0,
            "ids": [int(x) for x in key[:P0]],
            "block_tokens": self._paging.block_tokens,
        }
        payload = migration.encode_payload(header, {"k": hk, "v": hv})
        self._prefix_cache.move_to_end(key)  # a fetched chain is hot fleet-wide
        with self.stats_lock:
            self.prefix_exports_total += 1
            self.prefix_export_bytes_total += len(payload)
        self._flight.event(
            "prefix_out", tokens=P0, wire_bytes=len(payload),
            wall_ms=round((time.perf_counter() - t0) * 1e3, 1),
        )
        log.info(
            "prefix export: %d tokens, %.1f KB in %.1f ms",
            P0, len(payload) / 1024, (time.perf_counter() - t0) * 1e3,
        )
        return payload

    def _prefix_import_now(self, header: dict, trees: dict, nbytes_wire: int) -> bool:
        """Insert a wire-decoded chain into the local prefix cache (engine
        thread): ledger registration first (evicting LRU entries to fit,
        exactly like a local store), then pool-row uploads on the physical
        path or a device-array entry on the contiguous path."""
        P0 = int(header.get("P") or 0)
        ids = [int(x) for x in header.get("ids") or []]
        hk = trees.get("k")
        hv = trees.get("v")
        hv = {} if hv is None else hv
        # Only pow2 lengths insert: _match_prefix probes pow2 buckets and
        # insert_cached compiles per entry length — a peer's entries are
        # pow2 by construction (_maybe_store_prefix), so a violation means
        # a corrupt or foreign payload. Geometry must match the local
        # cache leaf-for-leaf (layers, heads, head dims): a peer running a
        # different model or cache layout never imports.
        if (
            P0 < self.PREFIX_MIN or P0 & (P0 - 1) or len(ids) != P0
            or hk is None
            or not self._prefix_wire_compat(hk, hv)
        ):
            with self.stats_lock:
                self.prefix_import_rejects_total += 1
            return False
        key = tuple(ids)
        if key in self._prefix_cache:
            self._prefix_cache.move_to_end(key)
            return True
        while not self._paging.prefix_can_fit(P0) and self._prefix_cache:
            self._evict_lru_prefix()
        if self._paging.prefix_register(key, P0) is None:
            with self.stats_lock:
                self.prefix_import_rejects_total += 1
            return False
        if self._phys is not None:
            if not self._import_prefix_physical(key, hk, hv):
                self._paging.prefix_release(key)
                self._phys.sweep(self._paging.alive)
                with self.stats_lock:
                    self.prefix_import_rejects_total += 1
                return False
            nbytes = sum(
                (x.size // (x.shape[1] * x.shape[3])) * P0 * x.dtype.itemsize
                for x in jax.tree.leaves((self._ck, self._cv))
            )
            ent = {"P": P0, "bytes": nbytes, "key": key}
        else:
            self._eid_ctr += 1
            eid = self._eid_ctr
            pk, pv = self._dx("pfximp", eid, hk, hv if hv is not None else {})
            nbytes = sum(
                x.size * x.dtype.itemsize for x in jax.tree.leaves((pk, pv))
            )
            ent = {"P": P0, "k": pk, "v": pv, "bytes": nbytes, "key": key,
                   "eid": eid}
        self._prefix_cache[key] = ent
        self._prefix_by_len.setdefault(P0, {})[key] = ent
        self._prefix_cache_bytes += nbytes
        with self._prefix_pub_lock:
            self._prefix_pub[key] = P0
        while self._prefix_cache_bytes > self._prefix_budget and self._prefix_cache:
            self._evict_lru_prefix()
        ok = key in self._prefix_cache  # budget smaller than the entry evicts it
        with self.stats_lock:
            if ok:
                self.prefix_imports_total += 1
                self.prefix_import_bytes_total += nbytes_wire
            else:
                self.prefix_import_rejects_total += 1
        if ok:
            self._flight.event("prefix_in", tokens=P0, wire_bytes=nbytes_wire)
            log.info(
                "prefix import: %d tokens, %.1f KB wire (%d entries)",
                P0, nbytes_wire / 1024, len(self._prefix_cache),
            )
        return ok

    def _prefix_wire_compat(self, hk, hv) -> bool:
        """Whether wire-decoded host KV trees match the local cache's
        geometry (same leaf set; same layer, head, and trailing dims) —
        everything except the slot and token axes, which import rewrites."""
        ref = (
            (self._pool_k, self._pool_v) if self._phys is not None
            else (self._ck, self._cv)
        )
        try:
            ref_leaves = jax.tree.leaves(ref)
            host_leaves = jax.tree.leaves((hk, hv))
        except Exception:
            return False
        if len(ref_leaves) != len(host_leaves):
            return False
        for p, h in zip(ref_leaves, host_leaves):
            if (
                h.ndim != p.ndim
                or h.shape[0] != p.shape[0]
                or h.shape[1] != 1
                or h.shape[2] != p.shape[2]
                or h.shape[4:] != p.shape[4:]
            ):
                return False
        return True

    def _import_prefix_physical(self, key: tuple, hk, hv) -> bool:
        """Upload a wire-decoded chain's blocks into fresh prefix-pool
        rows (one block-shaped dispatch per block — same executable for
        every chain length)."""
        lids = self._paging.prefix_ids(key)
        if lids is None:
            return False
        rows = self._phys.register_prefix(lids)
        if rows is None:
            return False
        bt = self._paging.block_tokens
        for j, prow in enumerate(rows):
            first = self._note_exec_shape("pool_put_host")
            t0 = time.perf_counter()
            self._dx(
                "pput", "host",
                _host_block(hk, j * bt, bt), _host_block(hv, j * bt, bt),
                int(prow),
            )
            if first:
                self._compile_obs("pool_put_host", (bt,),
                                  time.perf_counter() - t0)
        return True

    def prefix_tier_stats(self) -> dict[str, float]:
        """Fleet-prefix-tier observability block (engines_info, dashboard,
        /v1/debug/prefix)."""
        with self._prefix_pub_lock:
            chains = len(self._prefix_pub)
            longest = max(self._prefix_pub.values(), default=0)
        with self.stats_lock:
            return {
                "enabled": 1.0 if self._prefix_budget else 0.0,
                "chains": float(chains),
                "longest_tokens": float(longest),
                "exports_total": float(self.prefix_exports_total),
                "export_bytes_total": float(self.prefix_export_bytes_total),
                "imports_total": float(self.prefix_imports_total),
                "import_bytes_total": float(self.prefix_import_bytes_total),
                "import_rejects_total": float(self.prefix_import_rejects_total),
            }

    def _start_batch(self, batch: list[tuple[int, GenRequest, list[int]]]) -> None:
        """Admit up to admit_batch short prompts with ONE batched prefill
        dispatch. At 8B the prompt weight pass dominates admission cost;
        per-request prefill starves admissions badly enough to leave most
        slots idle (measured 102 tok/s at B=64 — vs the decode loop's ~1.9k)."""
        A = len(batch)
        Ab = 1 << (A - 1).bit_length()  # pow2 pad: bounded executable count
        bucket = self._bucket(max(len(ids) for _, _, ids in batch))
        tokens = np.zeros((Ab, bucket), dtype=np.int32)
        ipack = np.zeros((3 * Ab + 2,), dtype=np.int32)
        fpack = np.zeros((2 * Ab,), dtype=np.float32)
        ipack[Ab : 2 * Ab] = 1  # dummy rows: 1 harmless token
        fpack[Ab:] = 1.0  # top_p
        for i, (slot, req, ids) in enumerate(batch):
            tokens[i, : len(ids)] = ids
            ipack[i] = slot
            ipack[Ab + i] = len(ids)
            ipack[2 * Ab + i] = req.top_k
            fpack[i] = req.temperature
            fpack[Ab + i] = req.top_p
        ipack[3 * Ab] = A
        ipack[3 * Ab + 1] = self._next_counter()
        # constrained admissions: the first sampled token rides the same
        # fused dispatch, so its mask (start-state row) and bias must too
        cn_payload = self._cn_payload([req.cn for _, req, _ in batch], Ab)
        # ONE fused dispatch: prefill + cache inserts + device sampling-param
        # rows + first-token sample (see admit_fn)
        first = self._note_exec_shape("admit", Ab, bucket, cn_payload is not None)
        t0c = time.perf_counter()
        toks0 = self._dx("admit", tokens, ipack, fpack, cn_payload)
        t_call = time.perf_counter()  # jit returned; device running
        toks0 = np.asarray(toks0)  # host sync: first-call wall ≈ compile time
        if first:
            self._compile_obs("admit", (Ab, bucket), time.perf_counter() - t0c)
        else:
            self._sample_prefill_phase(
                "admit", t0c, t_call,
                sum(len(ids) for _, _, ids in batch), A,
            )
        # latency waterfall: the fused admit dispatch is synchronous wall
        # every batched prompt sat through — attribute it by token share
        admit_wall = time.perf_counter() - t0c
        tot_tok = sum(len(ids) for _, _, ids in batch) or 1
        for i, (slot, req, ids) in enumerate(batch):
            self._activate_state(slot, req, ids, int(toks0[i]))
            s = self._slots[slot]
            if s is not None:
                s.prefill_compute_s += admit_wall * (len(ids) / tot_tok)

    def _activate_state(
        self, slot: int, req: GenRequest, ids: list[int], tok0: int
    ) -> None:
        P = len(ids)
        # the slot's cache rows [0, P) now hold exactly this prompt's KV —
        # the moment to learn a shared prefix for future admissions
        self._maybe_store_prefix(slot, ids)
        self._recent_prompts.append(tuple(ids))
        s = _Slot(req=req, prompt_len=P, first_token_at=time.time())
        # the automaton cursor moves onto the slot BEFORE tok0 is emitted:
        # _process_token advances it for every token including the first
        s.cn = req.cn
        # prefix-hit provenance rides the _PrefillState onto the live slot
        # (still present here — _finish_prefill_group deletes it after);
        # preemption uses it to snapshot only the private rows
        st = self._prefills.get(slot)
        if st is not None and st.shared_len:
            s.shared_entry = st.shared_entry
            s.shared_len = st.shared_len
        if st is not None:
            # chunked-path prefill walls accumulated while mid-chunk carry
            # onto the live slot for the latency waterfall
            s.prefill_compute_s += st.prefill_s
        # ledger: batch-path admissions create their table here; the
        # chunked/prefix-hit paths already reserved one (ensure extends it)
        mgr = self._paging
        mgr.ensure_slot(slot, P)
        want = min(P + max(0, req.max_tokens) + self.decode_chunk, self.max_seq_len)
        shared_full = s.shared_len // mgr.block_tokens if s.shared_len else 0
        mgr.note_admit_cost(mgr.blocks_for(want) - shared_full)
        self._slots[slot] = s
        self._lengths[slot] = P
        self._last_tok[slot] = tok0
        self._temp[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p
        ttft_ms = (s.first_token_at - req.created_at) * 1000.0
        with self.stats_lock:
            self.total_requests += 1
            self._ttft_window.append((s.first_token_at, ttft_ms))
        self._flight.event(
            "admit", trace_id=self._tid(req), request_id=req.request_id[:8],
            slot=slot, prompt_tokens=P, ttft_ms=round(ttft_ms, 1),
        )
        self._anomaly.signal("ttft_burn", ttft_ms=ttft_ms)
        if req.trace_ctx:
            # retroactive spans from timestamps already stamped: the caller's
            # trace gets engine.admit (submit→pop) and engine.prefill
            # (pop→first token, i.e. TTFT minus queue time)
            tracer = tracing.get_tracer()
            admitted = req.admitted_at or req.created_at
            tracer.record(
                "engine.admit", req.created_at, admitted,
                parent=req.trace_ctx, attrs={"request_id": req.request_id},
            )
            tracer.record(
                "engine.prefill", admitted, s.first_token_at,
                parent=req.trace_ctx,
                attrs={
                    "request_id": req.request_id,
                    "prompt_tokens": P,
                    "ttft_ms": round((s.first_token_at - req.created_at) * 1000.0, 1),
                    # scheduler decision context at activation: the budget
                    # this prompt's last chunk rode in under, and whether the
                    # backlog has been outrunning the TTFT deadline
                    "prefill_token_budget": self._sched.last_budget,
                    "sched_starved_rounds": self._sched.starved_rounds,
                },
            )
        if self._verify_fn is not None:
            # seed the n-gram drafter with the prompt: prompt-lookup drafting
            # pays off exactly when completions quote the prompt (extraction,
            # code edits, RAG). _process_token appends every emitted token so
            # the index also covers generated history.
            s.spec = NGramDrafter(self.spec_min_ngram, self.spec_max_ngram)
            s.spec.extend(ids)
        # tok0's KV will be written at position P in the first decode round.
        self._emit_token(slot, s, tok0, pos=P - 1)
        if (
            self._migrate_outbox is not None
            and (req.migrate_after_prefill or self.migrate_after_prefill)
            and not s.done
            and not s.aborted
        ):
            # disaggregated mode: this engine spent the prefill and emitted
            # the first token; the decode-role peer continues from here
            self._migrate_export_slot(slot, s)

    def _prefill_backlog(self) -> int:
        """Prompt tokens not yet written for live mid-prefill slots."""
        return sum(
            len(st.ids) - st.done
            for st in self._prefills.values()
            if not st.aborted
        )

    def _chunk_shape(self, slot: int, cap: int = 0) -> tuple[int, int, int, int]:
        """(start, n, bucket, skey) for a mid-prefill slot's next chunk,
        with `cap` (>0) bounding n to the scheduler's remaining budget.

        bucket never runs past the cache row end — dynamic_update_slice would
        CLAMP the start index and silently overwrite earlier prompt KV
        (prompts are pre-truncated to max_seq_len - decode_chunk, so
        S - start > n always holds). skey statically bounds the PAST key
        range (bucketed for jit-cache reuse): early chunks of a long prompt
        don't pay an O(max_seq_len) score tensor."""
        st = self._prefills[slot]
        start = st.done
        n = min(self.prefill_chunk, len(st.ids) - start)
        if cap > 0:
            n = min(n, cap)
        bucket = min(pow2_bucket(n, self.prefill_chunk), self.max_seq_len - start)
        skey = (
            min(pow2_bucket(start, self.max_seq_len), self.max_seq_len)
            if start
            else min(128, self.max_seq_len)
        )
        return start, n, bucket, skey

    def _stage_prefill_group(
        self, n_active: int, reserved_tokens: int = 0
    ) -> _PrefillGroup | None:
        """Ask the scheduler for this round's prefill token budget and stage
        one batched chunk group under it: up to admit_batch mid-prefill slots
        whose next chunks share (bucket, skey) — the chunk weight pass is the
        cost, and batching amortizes it like _start_batch does for short
        prompts. Staging only; the group is dispatched fused with the decode
        round (_dispatch_decode) or standalone (_dispatch_prefill_group).
        `reserved_tokens` is chunk work this iteration already owes elsewhere
        (a speculative verify dispatch); it shrinks the budget so verify +
        prefill together stay inside the round's fair share."""
        # states the stall watchdog error-terminated while the loop was
        # wedged: reclaim silently (their consumers are gone)
        for slot in [
            s for s in self._prefill_q if self._prefills.get(s, None) is not None
            and self._prefills[s].aborted
        ]:
            self._prefill_q.remove(slot)
            del self._prefills[slot]
            self._paging.free_slot(slot)
            self._phys_reset(slot)
        if not self._prefill_q:
            self._sched.decide(0, n_active, 0.0)
            return None
        oldest = min(
            self._prefills[s].req.created_at for s in self._prefill_q
        )
        budget = self._sched.decide(
            self._prefill_backlog(), n_active, time.time() - oldest,
            reserved_tokens=reserved_tokens,
        )
        if budget <= 0:
            return None
        if self.ragged_prefill:
            return self._stage_ragged_group(budget)
        group: list[int] = []
        metas: list[tuple[int, _PrefillState, int]] = []
        try:  # staging bugs must also fail over to waiters
            first = self._prefill_q[0]
            _, f_n, f_bucket, f_skey = self._chunk_shape(first, cap=budget)
            group.append(first)
            used = f_n
            for slot in list(self._prefill_q)[1:]:
                if len(group) >= self.admit_batch or used >= budget:
                    break
                start2, n2, _, s2 = self._chunk_shape(
                    slot, cap=min(budget - used, f_bucket)
                )
                # join only on identical (bucket, skey): one executable per
                # group shape. n2 rides row raggedness (nvalid) inside
                # f_bucket, so a budget-trimmed tail row still joins.
                if s2 == f_skey and n2 > 0 and start2 + f_bucket <= self.max_seq_len:
                    group.append(slot)
                    used += n2
            Ab = 1 << (len(group) - 1).bit_length()
            tokens = np.zeros((Ab, f_bucket), dtype=np.int32)
            slots_arr = np.zeros((Ab,), dtype=np.int32)
            starts_arr = np.zeros((Ab,), dtype=np.int32)
            nv_arr = np.ones((Ab,), dtype=np.int32)
            total = 0
            rem = budget
            for i, slot in enumerate(group):
                st = self._prefills[slot]
                start, n, _, _ = self._chunk_shape(
                    slot, cap=min(rem, f_bucket) if i else budget
                )
                tokens[i, :n] = st.ids[start : start + n]
                slots_arr[i] = slot
                starts_arr[i] = start
                nv_arr[i] = n
                metas.append((slot, st, n))
                total += n
                rem -= n
            for i in range(len(group), Ab):  # pad rows dup row 0: identical writes
                tokens[i] = tokens[0]
                slots_arr[i] = slots_arr[0]
                starts_arr[i] = starts_arr[0]
                nv_arr[i] = nv_arr[0]
            return _PrefillGroup(
                metas=metas, tokens=tokens, slots_arr=slots_arr,
                starts_arr=starts_arr, nv_arr=nv_arr,
                bucket=f_bucket, skey=f_skey, n_tokens=total,
            )
        except Exception as e:
            self._fail_prefill_group(
                _PrefillGroup(
                    metas=metas or [
                        (s, self._prefills[s], 0)
                        for s in group or self._prefill_q
                        if s in self._prefills
                    ],
                    tokens=None, slots_arr=None, starts_arr=None,
                    nv_arr=None, bucket=0, skey=0, n_tokens=0,
                ),
                e,
            )
            return None

    def _stage_ragged_group(self, budget: int) -> _PrefillGroup | None:
        """Ragged staging (the tentpole path): pack up to admit_batch slots'
        next chunks back-to-back into ONE [T] token buffer with per-token
        (rowid, position) and per-row (slot, start) descriptors — no
        (bucket, skey) join constraint, no pad rows, and each row is charged
        its TRUE token count against the budget (the bucketed path charges
        true tokens too but dispatches bucket-padded compute; here the pad
        tail is only T - total ≤ the pow2 rounding). T rides the pow2 ladder
        capped at _ragged_cap, so every fill mix reuses one executable per
        packed length."""
        R = max(1, self.admit_batch)
        S = self.max_seq_len
        picked: list[tuple[int, _PrefillState, int, int]] = []
        metas: list[tuple[int, _PrefillState, int]] = []
        try:
            used = 0
            max_start = 0
            cap = min(budget, self._ragged_cap)
            for slot in list(self._prefill_q):
                if len(picked) >= R or used >= cap:
                    break
                st = self._prefills[slot]
                start = st.done
                n = min(self.prefill_chunk, len(st.ids) - start, cap - used)
                if n <= 0:
                    continue
                picked.append((slot, st, start, n))
                used += n
                max_start = max(max_start, start)
            if not picked:
                return None
            T = pow2_bucket(used, self._ragged_cap, floor=min(32, self._ragged_cap))
            tokens = np.zeros((T,), dtype=np.int32)
            rowids = np.full((T,), R, dtype=np.int32)  # pads → dropped writes
            positions = np.full((T,), S, dtype=np.int32)
            slots_arr = np.zeros((R,), dtype=np.int32)
            starts_arr = np.zeros((R,), dtype=np.int32)
            nv_arr = np.zeros((R,), dtype=np.int32)
            last_idx = np.zeros((R,), dtype=np.int32)
            off = 0
            for i, (slot, st, start, n) in enumerate(picked):
                tokens[off : off + n] = st.ids[start : start + n]
                rowids[off : off + n] = i
                positions[off : off + n] = np.arange(start, start + n)
                slots_arr[i] = slot
                starts_arr[i] = start
                nv_arr[i] = n
                last_idx[i] = off + n - 1
                metas.append((slot, st, n))
                off += n
            # the kernel arm ignores skey entirely (data-dependent block
            # trips) — pass 0 so TPU mints ONE executable per T; the XLA arm
            # (CPU) keeps the bucketed-style static past bound for compile
            # cache reuse without whole-S gathers on short prefixes.
            if self._ragged_impl == "kernel":
                skey = 0
            else:
                skey = (
                    min(pow2_bucket(max_start, S), S)
                    if max_start
                    else min(128, S)
                )
            return _PrefillGroup(
                metas=metas, tokens=tokens, slots_arr=slots_arr,
                starts_arr=starts_arr, nv_arr=nv_arr,
                bucket=T, skey=skey, n_tokens=used, ragged=True,
                rowids_arr=rowids, positions_arr=positions,
                last_idx_arr=last_idx,
            )
        except Exception as e:
            self._fail_prefill_group(
                _PrefillGroup(
                    metas=metas or [
                        (s, self._prefills[s], 0)
                        for s in self._prefill_q
                        if s in self._prefills
                    ],
                    tokens=None, slots_arr=None, starts_arr=None,
                    nv_arr=None, bucket=0, skey=0, n_tokens=0,
                ),
                e,
            )
            return None

    def _dispatch_prefill_group(self, group: _PrefillGroup) -> None:
        """Standalone chunk dispatch for a pure-prefill window (no decode
        rows active — nothing to fuse with). Synchronous: the measured wall
        feeds the scheduler's per-token prefill cost EMA."""
        try:
            maybe_fail(
                "engine.prefill", f"slots={[s for s, _, _ in group.metas]}"
            )
            if group.ragged:
                # packed ragged dispatch: compiled shape is (T, skey, phys)
                # only — fill mix rides the descriptors, not the executable
                first = self._note_exec_shape("pf_rag", group.bucket,
                                              group.skey,
                                              self._phys is not None)
                t0 = time.perf_counter()
                self._gid_ctr += 1
                group.gid = self._gid_ctr
                self._dx(
                    "ragged", group.gid, group.tokens, group.rowids_arr,
                    group.positions_arr, group.slots_arr, group.starts_arr,
                    group.last_idx_arr, group.skey, self._paged_payload(),
                )
                t_call = time.perf_counter()  # jit returned; device running
                jax.block_until_ready(self._ck)
                wall = time.perf_counter() - t0
                if first:
                    self._compile_obs(
                        "pf_rag",
                        (group.bucket, group.skey, self._phys is not None),
                        wall,
                    )
                else:
                    self._sample_prefill_phase(
                        "pf_rag", t0, t_call, group.n_tokens,
                        len(group.metas),
                    )
                self._sched.observe_prefill(
                    group.n_tokens, wall, padded_tokens=group.bucket
                )
                self._credit_prefill_wall(group, wall)
                self._flight.event(
                    "pf_rag", rows=len(group.metas), tokens=group.n_tokens,
                    packed=group.bucket, wall_ms=round(wall * 1e3, 2),
                )
                self._finish_prefill_group(group)
                return
            first = self._note_exec_shape("chunk", group.tokens.shape[0],
                                          group.bucket, group.skey,
                                          self._phys is not None)
            t0 = time.perf_counter()
            self._gid_ctr += 1
            group.gid = self._gid_ctr
            self._dx(
                "chunk", group.gid, group.tokens, group.slots_arr,
                group.starts_arr, group.nv_arr, group.skey,
                self._paged_payload(),
            )
            t_call = time.perf_counter()  # jit returned; device running
            jax.block_until_ready(self._ck)
            wall = time.perf_counter() - t0
            if first:
                self._compile_obs(
                    "chunk",
                    (group.tokens.shape[0], group.bucket, group.skey,
                     self._phys is not None), wall,
                )
            else:
                self._sample_prefill_phase(
                    "chunk", t0, t_call, group.n_tokens, len(group.metas),
                )
            self._sched.observe_prefill(
                group.n_tokens, wall,
                padded_tokens=group.tokens.shape[0] * group.bucket,
            )
            self._credit_prefill_wall(group, wall)
            self._flight.event(
                "chunk", rows=len(group.metas), tokens=group.n_tokens,
                bucket=group.bucket, wall_ms=round(wall * 1e3, 2),
            )
        except Exception as e:
            self._fail_prefill_group(group, e)
            return
        self._finish_prefill_group(group)

    def _credit_prefill_wall(self, group: _PrefillGroup, wall: float) -> None:
        """Latency waterfall: attribute a synchronous chunk-dispatch wall to
        the mid-prefill prompts that rode it, by valid-token share. (The
        fused chunk path has no synchronous wall — its share surfaces as
        prefill_queue, which is honest: the prompt rode a decode round.)"""
        tot = group.n_tokens or 1
        for _, st, n in group.metas:
            st.prefill_s += wall * (n / tot)

    def _finish_prefill_group(self, group: _PrefillGroup) -> None:
        """Advance chunk progress for a dispatched group and activate the
        prompts whose last chunk just landed (first-token sample from the
        group's prefill logits)."""
        try:
            fin: list[tuple[int, int, _PrefillState]] = []
            for i, (slot, st, n) in enumerate(group.metas):
                st.done += n
                if st.done >= len(st.ids):
                    fin.append((i, slot, st))
            # BATCHED activation: one first-token sample + one update per
            # device sampling array for the whole finishing group (per-slot
            # activation cost ~5 host<->device round trips — with
            # prefix-cache hits riding this path, that tax would dominate
            # admission again). Dispatched even with nothing finishing: the
            # op pops the group's parked logits on every process.
            rows = np.asarray([i for i, _, _ in fin], dtype=np.int32)
            slots_fin = np.asarray([s for _, s, _ in fin], dtype=np.int32)
            temps = np.asarray([st.req.temperature for _, _, st in fin], np.float32)
            topks = np.asarray([st.req.top_k for _, _, st in fin], np.int32)
            topps = np.asarray([st.req.top_p for _, _, st in fin], np.float32)
            # constrained slots finishing their chunked prefill sample
            # tok0 here: their start-state masks ride the same dispatch
            cn_payload = self._cn_payload(
                [st.req.cn for _, _, st in fin], len(fin)
            )
            toks0 = self._dx(
                "bsample", group.gid, rows, slots_fin, temps, topks, topps,
                self._next_counter(), cn_payload,
            )
            if fin:
                toks0 = np.asarray(toks0)
                for k, (_, slot, st) in enumerate(fin):
                    self._prefill_q.remove(slot)
                    # _prefills entry is dropped only AFTER activation
                    # succeeds: on a raise the except path below still finds
                    # the state and delivers error+_DONE to the waiter (it
                    # would hang forever otherwise)
                    self._activate_state(slot, st.req, st.ids, int(toks0[k]))
                    del self._prefills[slot]
        except Exception as e:
            self._fail_prefill_group(group, e)

    def _fail_prefill_group(self, group: _PrefillGroup, e: Exception) -> None:
        """Fail a chunk group's waiters and recover the cache if the failed
        dispatch consumed the donated buffers."""
        slots = [s for s, _, _ in group.metas]
        log.exception("chunked prefill failed (slots %s)", slots)
        for slot in slots:
            st = self._prefills.pop(slot, None)
            if st is not None:
                try:
                    self._prefill_q.remove(slot)
                except ValueError:
                    pass
                # free the slot if activation partially completed
                s = self._slots[slot]
                if s is not None and s.req is st.req:
                    self._free_now(slot)
                else:  # reserved-not-activated: release the ledger table
                    self._paging.free_slot(slot)
                    self._phys_reset(slot)
                if not st.aborted:  # watchdog may have terminated it already
                    self._count_error()
                    st.req.out.put({"type": "error", "error": str(e)})
                    st.req.out.put(_DONE)
        if self._recover_cache():
            self._abort_all("kv cache lost in failed prefill chunk")

    def _stage_spec(self, active: list[int]) -> list[tuple[int, list[int]]] | None:
        """Propose drafts for a speculative verify round, or None to keep the
        normal pipelined decode path.

        Every active slot joins the round (a slot with no n-gram match rides
        with zero drafts — its verify row degenerates to a single-token
        decode step, so nobody stalls), but the round only runs when a
        MAJORITY of slots actually have drafts: a verify dispatch costs a
        C-wide chunk pass and forces a pipeline drain, so it must beat the
        K-token decode round it displaces.

        Hard precondition: every row must satisfy len + C <= S, because
        dynamic_update_slice CLAMPS out-of-range starts — a clamped verify
        write would silently overwrite live KV. One near-cap slot falls the
        whole round back to normal decode (it will finish within a few
        rounds and unblock speculation)."""
        if not active:
            return None
        C = self.spec_k + 1
        S = self.max_seq_len
        entries: list[tuple[int, list[int]]] = []
        n_drafting = 0
        for b in active:
            s = self._slots[b]
            if s is None or s.spec is None:
                return None
            if int(self._lengths[b]) + C > S:
                return None
            d = s.spec.draft(self.spec_k)
            if d and s.cn is not None:
                # spec × constraint composition: truncate the draft to its
                # longest automaton-legal prefix, so every draft position
                # verify scores is constraint-legal BY CONSTRUCTION and a
                # masked target can never be asked to accept an illegal
                # token (it would always reject — wasted verify width)
                d = s.cn.filter_draft(d)
            if d:
                n_drafting += 1
            entries.append((b, d))
        if n_drafting == 0 or 2 * n_drafting < len(entries):
            return None
        return entries

    def _spec_round(self, entries: list[tuple[int, list[int]]]) -> None:
        """Dispatch one speculative verify round SYNCHRONOUSLY (the pipeline
        is already drained): one chunk pass over [token, draft_1..draft_nd]
        per slot, accept the longest agreeing prefix, emit accepted drafts +
        the device-sampled final token, and roll lengths forward to the
        accepted position. Rollback on rejection is pure arithmetic: cache
        rows past base+n_acc are dead (chunk attention masks key_pos >=
        start per row, decode attends < length, later writes land in place),
        so nothing is erased."""
        maybe_fail("engine.verify", f"slots={[b for b, _ in entries]}")
        t0 = time.perf_counter()
        B = self.max_slots
        Kd = self.spec_k
        C = Kd + 1
        n = len(entries)
        A = 1 << (n - 1).bit_length()
        tokens = np.zeros((A, C), dtype=np.int32)
        slots_arr = np.full((A,), B, dtype=np.int32)  # pads OOB: writes drop
        starts_arr = np.zeros((A,), dtype=np.int32)
        nv_arr = np.ones((A,), dtype=np.int32)
        drafts_arr = np.zeros((A, Kd), dtype=np.int32)
        nd_arr = np.zeros((A,), dtype=np.int32)
        total = 0
        for i, (b, d) in enumerate(entries):
            nd = len(d)
            tokens[i, 0] = self._last_tok[b]
            if nd:
                tokens[i, 1 : 1 + nd] = d
                drafts_arr[i, :nd] = d
            slots_arr[i] = b
            starts_arr[i] = self._lengths[b]
            nv_arr[i] = 1 + nd
            nd_arr[i] = nd
            total += 1 + nd
        skey = min(
            pow2_bucket(int(starts_arr[:n].max()), self.max_seq_len),
            self.max_seq_len,
        )
        # constrained verify rounds (reached only via _cn_round, so the
        # round is HOMOGENEOUS — every live row carries an automaton):
        # per-position packed masks + the per-request bias arrays ride the
        # payload; pad rows/positions stay all-ones (spec_verify never
        # reads past each row's valid draft span)
        cn_objs = [self._slots[b].cn for b, _ in entries]
        constrained = any(c is not None for c in cn_objs)
        cn_payload = None
        if constrained:
            t_m = time.perf_counter()
            W = constrain.mask_words(self.cfg.vocab_size)
            NB = self.cn_bias_max
            masks = np.full((A, C, W), 0xFFFFFFFF, dtype=np.uint32)
            bids = np.full((A, NB), -1, dtype=np.int32)
            bvals = np.zeros((A, NB), dtype=np.float32)
            for i, (b, d) in enumerate(entries):
                cn = cn_objs[i]
                if cn is None:
                    continue
                rows = cn.masks_for_draft(d)
                masks[i, : rows.shape[0]] = rows
                nb = min(len(cn.bias_ids), NB)
                if nb:
                    bids[i, :nb] = cn.bias_ids[:nb]
                    bvals[i, :nb] = cn.bias_vals[:nb]
            self.cn_mask_s += time.perf_counter() - t_m
            cn_payload = (masks, bids, bvals)
        first = self._note_exec_shape("verify", A, C, skey,
                                      self._phys is not None, constrained)
        n_acc, final = self._dx(
            "verify", tokens, slots_arr, starts_arr, nv_arr, drafts_arr,
            nd_arr, self._next_counter(), skey, self._paged_payload(),
            cn_payload,
        )
        t_call = time.perf_counter()  # jit returned (dispatch is async)
        n_acc = np.asarray(n_acc)  # the round's host sync point
        final = np.asarray(final)
        if first:
            self._compile_obs(
                "verify", (A, C, skey, self._phys is not None, constrained),
                time.perf_counter() - t0,
            )
        elif self._perf.should_sample("verify"):
            # verify is synchronous, so the asarray fetch IS the device wall
            t_done = time.perf_counter()
            wait_s = max(0.0, t0 - self._perf_mark)
            self._perf.observe_phase(
                "verify", t_call - t0, t_done - t_call, wait_s,
                tokens=total, rows=n,
                ctx_mean=float(starts_arr[:n].mean()) if n else 0.0,
            )
            self._flight.event(
                "perf", phase="verify",
                host_ms=round((t_call - t0) * 1e3, 3),
                device_ms=round((t_done - t_call) * 1e3, 3),
                wait_ms=round(wait_s * 1e3, 3),
                rows=n,
            )
        self._sched.observe_verify(total, time.perf_counter() - t0)
        before = self.total_tokens
        drafted_round = 0
        accepted_round = 0
        blk_wants: dict[int, int] = {}
        for i, (b, d) in enumerate(entries):
            s = self._slots[b]
            if s is None or s.done:
                continue
            if s.aborted:
                # watchdog delivered the terminal error mid-call
                self._free_now(b)
                continue
            na = min(int(n_acc[i]), len(d))
            base_b = int(starts_arr[i])
            drafted_round += len(d)
            accepted_round += na
            s.spec_drafted += len(d)
            s.spec_accepted += na
            toks = list(d[:na]) + [int(final[i])]
            parts: list[str] = []
            finish = None
            emitted = 0
            gen_before = s.generated
            for j, tok in enumerate(toks):
                emit, finish = self._process_token(s, int(tok), base_b + j)
                if int(tok) != self.tokenizer.eos_id:
                    emitted += 1  # mirrors _process_token's counting rule
                if emit:
                    parts.append(emit)
                if finish is not None:
                    break
            self.spec_emitted += emitted
            self._observe_itl(s, s.generated - gen_before)
            if parts:
                s.req.out.put({"type": "token", "text": "".join(parts)})
            if finish is not None:
                self._finish_slot(b, s, finish)
            else:
                # commit: KV valid through base+na (token + accepted
                # drafts); `final`'s KV is written by the next round
                self._lengths[b] = base_b + 1 + na
                self._last_tok[b] = int(final[i])
                blk_wants[b] = base_b + 1 + na
        if blk_wants:
            self._paging.extend_many(blk_wants)
        self.spec_calls += 1
        self.spec_drafted += drafted_round
        self.spec_accepted += accepted_round
        self._last_round_ts = time.time()  # verify rounds are cadence too
        self._flight.event(
            "verify", rows=n, drafted=drafted_round, accepted=accepted_round,
        )
        if constrained:
            # spec × constraint composition telemetry: how much of the
            # filtered draft stream survives the masked target
            self.cn_spec_drafted += drafted_round
            self.cn_spec_accepted += accepted_round
            self._flight.event(
                "cn_spec", rows=n, drafted=drafted_round,
                accepted=accepted_round,
            )
        self._anomaly.signal(
            "spec_collapse", drafted=drafted_round, accepted=accepted_round
        )
        if drafted_round and accepted_round * 4 < drafted_round:
            # drafts aren't landing (workload shifted away from its own
            # history): a verify round still emits >=1 token per slot, but a
            # decode round emits K — back off before re-probing
            self._spec_cooldown = 50
        with self.stats_lock:
            self._window.append((time.time(), self.total_tokens - before))

    def _cn_round(self, cn_active: list[int]) -> None:
        """One synchronous round for the constrained slots. Constrained
        traffic composes with speculation first: when the n-gram drafters
        have automaton-filtered drafts for a majority of constrained slots,
        the round IS a masked verify (_spec_round with the cn payload —
        per-position masks applied before accept/reject, so the committed
        tokens follow the renormalized masked target exactly). Otherwise
        one masked single decode step (op "cnstep"). Either way the round
        commits before returning: constrained slots are never pipelined,
        because the mask for token t+1 only exists after the host automaton
        consumed token t."""
        if self._verify_fn is not None and self._spec_cooldown <= 0:
            entries = self._stage_spec(cn_active)
            if entries is not None:
                self._spec_round(entries)
                return
        self._cn_step_round(cn_active)

    def _cn_step_round(self, cn_active: list[int]) -> None:
        """Masked single-step decode round: gather each slot automaton's
        current packed mask row + bias arrays, dispatch op "cnstep", and
        commit the sampled token through _process_token (which advances
        the automaton for the NEXT round's masks)."""
        maybe_fail("engine.cnstep", f"slots={cn_active}")
        t0 = time.perf_counter()
        B = self.max_slots
        S = self.max_seq_len
        n = len(cn_active)
        Ba = pow2_bucket(n, B, floor=min(8, B))
        act = np.asarray(cn_active, dtype=np.int32)
        if Ba > n:
            # pad rows must target an inactive cache row (the same append-
            # tile safety rule as _dispatch_decode's compact path)
            in_round = set(cn_active)
            free = next(
                (i for i in range(B)
                 if self._slots[i] is None and i not in self._prefills),
                next(
                    (i for i in range(B) if self._slots[i] is None),
                    next(i for i in range(B) if i not in in_round),
                ),
            )
        else:
            free = 0  # Ba == n: no pad rows exist
        ids = np.full(Ba, free, dtype=np.int32)
        ids[:n] = act
        lens_in = np.full(Ba, S, dtype=np.int32)
        lens_in[:n] = self._lengths[act]
        packed = np.concatenate(
            [lens_in, ids, [self._next_counter()]]
        ).astype(np.int32)
        # host mask gather: memoized per automaton state, so steady-state
        # cost is a dict hit + row copy per slot (cn_mask_s / cn_tokens is
        # the published mask_us_per_tok)
        masks, bids, bvals = self._cn_payload(
            [self._slots[b].cn for b in cn_active], Ba
        )
        first = self._note_exec_shape("cnstep", Ba, self._phys is not None)
        toks = self._dx(
            "cnstep", packed, masks, bids, bvals, self._paged_payload()
        )
        t_call = time.perf_counter()
        toks = np.asarray(toks)  # synchronous round: this is the device wall
        if first:
            self._compile_obs("cnstep", (Ba, self._phys is not None),
                              time.perf_counter() - t0)
        elif self._perf.should_sample("cnstep"):
            t_done = time.perf_counter()
            wait_s = max(0.0, t0 - self._perf_mark)
            self._perf.observe_phase(
                "cnstep", t_call - t0, t_done - t_call, wait_s,
                tokens=n, rows=n,
                ctx_mean=float(lens_in[:n].mean()) if n else 0.0,
            )
            self._flight.event(
                "perf", phase="cnstep",
                host_ms=round((t_call - t0) * 1e3, 3),
                device_ms=round((t_done - t_call) * 1e3, 3),
                wait_ms=round(wait_s * 1e3, 3),
                rows=n,
            )
        before = self.total_tokens
        blk_wants: dict[int, int] = {}
        for i, b in enumerate(cn_active):
            s = self._slots[b]
            if s is None or s.done:
                continue
            if s.aborted:
                self._free_now(b)
                continue
            pos = int(self._lengths[b])
            gen_before = s.generated
            emit, finish = self._process_token(s, int(toks[i]), pos)
            self._observe_itl(s, s.generated - gen_before)
            if emit:
                s.req.out.put({"type": "token", "text": emit})
            if finish is not None:
                self._finish_slot(b, s, finish)
            else:
                self._lengths[b] = pos + 1
                self._last_tok[b] = int(toks[i])
                blk_wants[b] = pos + 1
        if blk_wants:
            self._paging.extend_many(blk_wants)
        self._last_round_ts = time.time()  # cn rounds are decode cadence too
        self._flight.event("cnstep", rows=n)
        with self.stats_lock:
            self._window.append((time.time(), self.total_tokens - before))

    def _dispatch_decode(
        self, active: list[int], group: _PrefillGroup | None = None
    ) -> _DispatchedRound:
        """Phase 1: stage host inputs and dispatch one decode round (NO
        fetch — the returned round is in flight on device). Input tokens
        come from the device-resident ring (decode_chunk_fn), so this never
        waits on an earlier round's output; host lengths advance
        OPTIMISTICALLY here (+K per dispatched row — the device really does
        advance them), which is what lets the next dispatch stage correct
        write positions before this round is fetched.

        With a staged prefill chunk `group`, the round goes through
        fused_step_fn: the same dispatch also writes the group's prompt
        tokens (budget-bounded, slot-disjoint from the active rows) and
        parks its boundary logits un-fetched on the dispatch plane
        (_x_logits[group.gid]) for the activation sample."""
        # chaos site: a failed round must fail active slots with error
        # events, not hang callers (the poisoned-round guard in _run)
        maybe_fail("engine.decode", f"active={len(active)}")
        round_t0 = time.perf_counter()
        B = self.max_slots
        nact = len(active)
        self._last_active_n = nact
        # Slot compaction: dispatch a pow2 bucket of just the active rows.
        # Floor 8 bounds the executable count (8, 16, 32, ... B); at Ba == B
        # the full-batch trace (slot_ids=None) is reused instead — identical
        # math, no indirection.
        Ba = pow2_bucket(nact, B, floor=min(8, B)) if self.decode_compact else B
        compact = Ba < B
        if compact:
            act = np.asarray(active, dtype=np.int32)
            # Pad rows MUST target an INACTIVE cache row: pads are parked
            # (length = S ⇒ the append kernels write nothing live), but each
            # pallas grid cell still rewrites its target tile — aimed at an
            # active row, a pad cell ordered after that row's real cell could
            # write back a PRE-append tile and silently drop the append.
            # Prefer a row that is neither active nor mid-chunked-prefill —
            # those hold garbage by definition, so the no-op rewrite (and the
            # attend kernel's discarded read) is trivially harmless. A
            # mid-prefill row is still value-safe (parked pads write back
            # byte-identical tiles; fallbacks drop OOB scatters) but only a
            # last resort — as is an occupied-but-undispatchable row (at the
            # context cap awaiting its fetch; possible only under the
            # pipelined loop's dispatch filter): its pad cell reads the
            # post-append tile (device stream is in-order) and writes it
            # back unchanged. The one UNSAFE target is a row active in THIS
            # dispatch (its real cell and the pad cell race within one
            # kernel launch) — and compact (Ba < B ⇒ nact < B) guarantees a
            # non-active row exists.
            in_round = set(active)
            free = next(
                (i for i in range(B)
                 if self._slots[i] is None and i not in self._prefills),
                next(
                    (i for i in range(B) if self._slots[i] is None),
                    next(i for i in range(B) if i not in in_round),
                ),
            )
            ids = np.full(Ba, free, dtype=np.int32)
            ids[:nact] = act
            lens_in = np.full(Ba, self.max_seq_len, dtype=np.int32)
            lens_in[:nact] = self._lengths[act]
            # ONE packed transfer per round (see decode_chunk_fn docstring)
            packed = np.concatenate(
                [lens_in, ids, [self._next_counter()]]
            ).astype(np.int32)
        else:
            packed = np.concatenate(
                [self._lengths, [self._next_counter()]]
            ).astype(np.int32)
        base = self._lengths.copy()
        if group is not None:
            maybe_fail(
                "engine.prefill", f"slots={[s for s, _, _ in group.metas]}"
            )
            if group.ragged:
                first = self._note_exec_shape(
                    "fused_rag", Ba, compact, group.bucket, group.skey,
                    self._phys is not None,
                )
                t0c = time.perf_counter()
                self._gid_ctr += 1
                group.gid = self._gid_ctr
                out = self._dx(
                    "decode", "fusedrag", group.gid, packed,
                    (group.tokens, group.rowids_arr, group.positions_arr,
                     group.slots_arr, group.starts_arr, group.last_idx_arr),
                    compact, group.skey, self._paged_payload(),
                )
                if first:
                    self._compile_obs(
                        "fused_rag",
                        (Ba, compact, group.bucket, group.skey,
                         self._phys is not None),
                        time.perf_counter() - t0c,
                    )
            else:
                first = self._note_exec_shape(
                    "fused", Ba, compact, group.tokens.shape[0],
                    group.bucket, group.skey, self._phys is not None,
                )
                t0c = time.perf_counter()
                self._gid_ctr += 1
                group.gid = self._gid_ctr
                out = self._dx(
                    "decode", "fused", group.gid, packed,
                    (group.tokens, group.slots_arr, group.starts_arr,
                     group.nv_arr),
                    compact, group.skey, self._paged_payload(),
                )
                if first:
                    # dispatch is async but jit trace+compile is synchronous
                    # — the first call's wall time is dominated by the
                    # compile
                    self._compile_obs(
                        "fused",
                        (Ba, compact, group.tokens.shape[0], group.bucket,
                         group.skey, self._phys is not None),
                        time.perf_counter() - t0c,
                    )
        else:
            first = self._note_exec_shape("decode", Ba, compact,
                                          self._phys is not None)
            t0c = time.perf_counter()
            out = self._dx(
                "decode", "plain", 0, packed, (), compact, 0,
                self._paged_payload(),
            )
            if first:
                self._compile_obs(
                    "decode", (Ba, compact, self._phys is not None),
                    time.perf_counter() - t0c,
                )
        entries = [
            (b, self._slots[b], (i if compact else b)) for i, b in enumerate(active)
        ]
        # optimistic advance: the device WILL move every dispatched row K
        # steps; later dispatches must stage post-round positions without
        # waiting for this round's fetch. Capped at S (parking invariant).
        for b in active:
            self._lengths[b] = min(int(base[b]) + self.decode_chunk,
                                   self.max_seq_len)
        # ledger: grow block tables to cover the advanced lengths (batched —
        # one lock acquisition per round; a no-op inside a block)
        self._paging.extend_many({b: int(self._lengths[b]) for b in active})
        self._rid_dispatched += 1
        if group is not None:
            padded = (
                group.bucket if group.ragged
                else group.tokens.shape[0] * group.bucket
            )
        else:
            padded = 0
        phase_name = (
            ("fused_rag" if group.ragged else "fused")
            if group is not None else "decode"
        )
        self._flight.event(
            phase_name,
            rid=self._rid_dispatched, rows=len(active),
            prefill_tokens=group.n_tokens if group is not None else 0,
            prefill_padded=padded,
        )
        # Sampled steady-state attribution (every Nth dispatch of this
        # phase; first dispatches belong to the CompileLedger): host = the
        # staging+dispatch wall up to the async jit return, device = one
        # block_until_ready on the round (the sample's cost — it serializes
        # the pipeline for this round only), wait = the host-side gap since
        # the previous round's fetch landed.
        if not first and self._perf.should_sample(phase_name):
            t1 = time.perf_counter()
            jax.block_until_ready(out)
            t2 = time.perf_counter()
            wait_s = max(0.0, round_t0 - self._perf_mark)
            ctx_mean = float(base[active].mean()) if nact else 0.0
            self._perf.observe_phase(
                phase_name, t1 - round_t0, t2 - t1, wait_s,
                tokens=nact * self.decode_chunk, rows=nact,
                ctx_mean=ctx_mean,
            )
            self._flight.event(
                "perf", phase=phase_name,
                host_ms=round((t1 - round_t0) * 1e3, 3),
                device_ms=round((t2 - t1) * 1e3, 3),
                wait_ms=round(wait_s * 1e3, 3),
                rows=nact,
            )
        return _DispatchedRound(
            out=out, entries=entries, base=base, t0=round_t0,
            rid=self._rid_dispatched,
            prefill_tokens=group.n_tokens if group is not None else 0,
            prefill_padded=padded,
        )

    def _complete_round(self, disp: _DispatchedRound) -> _PendingRound:
        """Phase 2 (the per-round sync point): fetch the round, fast-scan
        finishes so the NEXT dispatch excludes finishing slots, and advance
        the host mirrors. Token emission is deferred (_emit_round) so it
        overlaps the next round's device time.

        The fast-scan duplicates ONLY _emit_token's counter-based finish
        rules (eos, max_tokens, seq-len cap) — a strict SUBSET of emission's
        rules (which add stop sequences), so a fast-scan finish always
        implies an emission finish on the same tokens; emission stays
        authoritative for events, usage, and text."""
        out = np.asarray(disp.out)  # [K, Ba] — the only host sync per round
        self._last_round_ts = time.time()  # decode-cadence stall signal
        self._perf_mark = time.perf_counter()  # sampled wait-gap anchor
        # feed the token-budget scheduler's cost model: prefill-free rounds
        # teach the decode-round EMA; fused rounds attribute their time over
        # that EMA to the chunk group's prompt tokens
        dt = time.perf_counter() - disp.t0
        if disp.prefill_tokens:
            self._sched.observe_fused(
                dt, disp.prefill_tokens, padded_tokens=disp.prefill_padded
            )
        else:
            self._sched.observe_decode(dt)
        K = out.shape[0]
        S = self.max_seq_len
        eos = self.tokenizer.eos_id
        # Device advanced every dispatched row K steps; mirror that for rows
        # still owned by the SAME request (identity check: a slot freed by a
        # stop-sequence finish and re-admitted between dispatch and fetch
        # owns its new lengths — never touch them). Parked rows stay pinned
        # at exactly max_seq_len (drifting past it would eventually wrap
        # int32 back into [0, S) and break the OOB-drop parking invariant —
        # see __init__).
        for b, s, col in disp.entries:
            if self._slots[b] is not s:
                continue  # freed (and possibly re-admitted) since dispatch
            if s.aborted:
                # stall watchdog already delivered this consumer's terminal
                # error while the loop was wedged — reclaim the slot now
                # instead of decoding garbage until the seq cap
                self._free_now(b)
                continue
            g = s.generated
            fin = False
            base_b = int(disp.base[b])
            for k in range(K):
                if int(out[k, col]) == eos:
                    fin = True
                    break
                g += 1
                if g >= s.req.max_tokens:
                    fin = True
                    break
                if base_b + k + 1 + K > S:
                    fin = True
                    break
            if fin:
                # free NOW: the next dispatch must exclude this slot and
                # admission may reuse it (after the cooling fence — rounds
                # already in flight still reference the row); the deferred
                # emission delivers its events from the pinned slot object
                self._free_now(b)
            else:
                # lengths were advanced optimistically at dispatch (the
                # pipelined loop stages later rounds before this fetch) —
                # only the recovery mirror updates here
                self._last_tok[b] = out[-1, col]
        self._rid_fetched = max(self._rid_fetched, disp.rid)
        return _PendingRound(out=out, entries=disp.entries, base=disp.base)

    def _free_now(self, b: int) -> None:
        """Park a slot and fence its reuse until every round currently in
        flight (which may still write the row's cache tiles / token-ring
        entry) has been fetched."""
        self._slots[b] = None
        self._lengths[b] = self.max_seq_len  # park
        # ledger: drop the slot's block table (idempotent no-op when the
        # table is already gone — e.g. preempt parked it under a snap_id);
        # physical: the device table row back to identity + pool-row sweep
        self._paging.free_slot(b)
        self._phys_reset(b)
        if self._rid_dispatched > self._rid_fetched:
            self._cooling[b] = self._rid_dispatched

    def _emit_round(self, p: _PendingRound) -> None:
        """Phase 3 (deferred, overlapped with the next round's device time):
        decode token text, deliver events, finalize usage/finishes."""
        K = p.out.shape[0]
        before = self.total_tokens  # _process_token counts delivered tokens
        for b, s, col in p.entries:
            if s.done or s.aborted:
                continue  # terminal event already delivered
            parts: list[str] = []
            finish = None
            base_b = int(p.base[b])
            gen_before = s.generated
            for k in range(K):
                emit, finish = self._process_token(s, int(p.out[k, col]), base_b + k)
                if emit:
                    parts.append(emit)
                if finish is not None:
                    break
            self._observe_itl(s, s.generated - gen_before)
            if parts:
                # ONE coalesced text event per slot per round: the K tokens
                # were all learned at the same fetch, so splitting them into
                # K queue events (and K SSE frames) adds overhead with zero
                # client-visible timing difference
                s.req.out.put({"type": "token", "text": "".join(parts)})
                if self._pool is not None:
                    # the "idle" preemption policy's victim signal; guarded
                    # so the pool-off hot path writes nothing
                    s.last_emit = time.time()
            if finish is not None:
                self._finish_slot(b, s, finish)
        with self.stats_lock:
            self._window.append((time.time(), self.total_tokens - before))

    def _sample_prefill_phase(
        self, phase: str, t0: float, t_call: float, tokens: int, rows: int
    ) -> None:
        """Sampled attribution for the synchronous prefill-family
        dispatches, called right after their device sync: t0→t_call is host
        staging (the jit call returns as soon as the dispatch is queued),
        t_call→now is device compute. Every Nth dispatch per phase
        (TPU_PERF_SAMPLE); first dispatches never reach here (they are the
        CompileLedger's)."""
        if not self._perf.should_sample(phase):
            return
        t_done = time.perf_counter()
        wait_s = max(0.0, t0 - self._perf_mark)
        self._perf.observe_phase(
            phase, t_call - t0, t_done - t_call, wait_s,
            tokens=tokens, rows=rows,
        )
        self._flight.event(
            "perf", phase=phase,
            host_ms=round((t_call - t0) * 1e3, 3),
            device_ms=round((t_done - t_call) * 1e3, 3),
            wait_ms=round(wait_s * 1e3, 3),
            rows=rows,
        )

    def _observe_itl(self, s: _Slot, n_new: int) -> None:
        """Fold one emission round's tokens into the slot's token timeline:
        the wall gap since the slot's previous emission (first round: since
        its TTFT stamp) spread evenly over the round's tokens — the engine
        learns a round's tokens at ONE fetch, so a finer per-token split
        would be fiction. Feeds the observatory's ITL window/goodput and
        the itl_degradation anomaly detector."""
        if n_new <= 0:
            return
        now = time.time()
        anchor = s.perf_last_emit or s.first_token_at or now
        gap = max(0.0, now - anchor)
        itl = self._perf.observe_itl(gap, n_new)
        s.perf_last_emit = now
        s.itl_s_total += gap
        s.itl_samples += n_new
        # latency waterfall: the part of an emission gap beyond the stall
        # threshold is decode time the request did NOT spend computing its
        # own tokens (compile pause, preempt-adjacent churn, wedged link)
        thr = workload.stall_threshold_s()
        if gap > thr:
            s.stall_s += gap - thr
        self._anomaly.signal("itl_degradation", itl_ms=itl * 1e3)

    def _emit_token(self, slot_idx: int, s: _Slot, tok: int, pos: int) -> bool:
        """Append one token to a slot; returns False when the slot finished.

        `pos` is the cache position this token's KV occupies (or will occupy,
        for the prefill's first sample). The slot must finish while the next
        decode chunk's K writes still fit: pos+1+K ≤ max_seq_len.

        `s` is the slot OBJECT captured at dispatch time: under the
        pipelined loop the table entry may already be freed (fast
        finish-scan) or re-owned by a newer request — table mutations are
        identity-guarded (_finish_slot)."""
        emit, finish = self._process_token(s, tok, pos)
        if emit:
            s.req.out.put({"type": "token", "text": emit})
        if finish is not None:
            self._finish_slot(slot_idx, s, finish)
            return False
        return True

    def _process_token(self, s: _Slot, tok: int, pos: int) -> tuple[str, str | None]:
        """Advance one slot by one token WITHOUT delivering events: returns
        (text to emit, finish_reason | None). Event delivery is the caller's
        job so _emit_round can coalesce a whole round's text into ONE queue
        event per slot — the engine only learns tokens once per round, so
        per-token events add queue/SSE overhead with zero timing benefit."""
        req = s.req
        finish = None
        emit = ""
        cut = -1
        if s.cn is not None:
            # the single automaton hook for every emission path (admit tok0,
            # decode rounds, verify commits, cn steps): consume the token so
            # the next mask reflects it. The mask made an illegal token
            # impossible — cn_illegal is the live proof (must stay 0).
            self.cn_tokens += 1
            if not s.cn.advance(tok):
                self.cn_illegal += 1
        if tok == self.tokenizer.eos_id:
            finish = "stop"
        else:
            s.generated += 1
            # counted HERE (not per decode round) so a slot's finishing token
            # — and the prefill's first sample — aren't dropped from stats.
            # No lock: the engine thread is the ONLY writer (readers see a
            # plain int); taking stats_lock per token would mean ~B×K lock
            # round-trips per decode round.
            self.total_tokens += 1
            if s.spec is not None:
                s.spec.append(tok)
            text, s.pending = self.tokenizer.decode_stream(s.pending, [tok])
            # Stop sequences trim BEFORE emission (OpenAI/Ollama semantics:
            # the stop string itself is never delivered). Scan the window
            # where a stop could straddle the old/new text boundary.
            prev_len = len(s.text)
            total = s.text + text
            cut = -1
            for stop_s in req.stop:
                if not stop_s:
                    continue
                i = total.find(stop_s, max(0, prev_len - len(stop_s) + 1))
                if i != -1 and (cut == -1 or i < cut):
                    cut = i
            if cut != -1:
                emit = total[prev_len:cut]
                s.text = total[:cut]
                finish = "stop"
            else:
                emit = text
                s.text = total
            if finish is None and s.generated >= req.max_tokens:
                finish = "length"
            if finish is None and pos + 1 + self.decode_chunk > self.max_seq_len:
                finish = "length"
        if finish is not None and s.pending:
            # End of stream: flush any buffered partial decode (unless we cut
            # at a stop sequence — the buffered tail is post-stop text).
            if cut == -1:
                emit += self.tokenizer.decode_flush(s.pending)
            s.pending = b""
        return emit, finish

    def _finish_slot(self, slot_idx: int, s: _Slot, finish: str) -> None:
        """Deliver a slot's terminal events and release its table entry."""
        req = s.req
        s.done = True
        # counters move BEFORE the done/_DONE events publish: a caller
        # unblocked by the queue must never observe stale counters
        with self.stats_lock:
            self.finished_requests += 1
            self.finished_tokens += s.generated
        if s.cn is not None and s.cn.constrained:
            # schema validity at the REQUEST level: a constrained stream
            # that ends anywhere but an accepting automaton state produced
            # a syntactically incomplete document (e.g. cut by max_tokens)
            self.cn_finished += 1
            if s.cn.accepting:
                self.cn_finished_accepting += 1
        ttft_ms = (s.first_token_at - req.created_at) * 1000.0
        itl_mean_ms = (
            s.itl_s_total / s.itl_samples * 1e3 if s.itl_samples else 0.0
        )
        # goodput ledger: classify against the joint TTFT+ITL SLO (the
        # tenant id lands the request in that tenant's ledger too)
        if s.first_token_at:
            self._perf.finish_request(
                ttft_ms, itl_mean_ms, s.generated, tenant=req.tenant
            )
        if req.tenant:
            # bill the tenant's token bucket: prompt + generated tokens
            # drain the quota the admission gate refills against
            self._sched.tenant_charge(req.tenant, s.prompt_len + s.generated)
        # record BEFORE the done/_DONE events publish: a caller unblocked by
        # the queue must be able to see the completed trace immediately
        if req.trace_ctx and s.first_token_at:
            now = time.time()
            dur = max(now - s.first_token_at, 1e-9)
            attrs = {
                "request_id": req.request_id,
                "completion_tokens": s.generated,
                "output_tokens": s.generated,
                "tok_per_s": round(s.generated / dur, 1),
                "itl_mean_ms": round(itl_mean_ms, 2),
                "finish_reason": finish,
            }
            if s.spec is not None:
                # speculation contribution to this stream: drafted vs
                # accepted counts explain the tok_per_s figure
                attrs["spec_drafted"] = s.spec_drafted
                attrs["spec_accepted"] = s.spec_accepted
            if s.cn is not None and s.cn.constrained:
                attrs["cn_accepting"] = bool(s.cn.accepting)
            tracing.get_tracer().record(
                "engine.decode", s.first_token_at, now,
                parent=req.trace_ctx, attrs=attrs,
            )
        # Latency waterfall (telemetry/workload.py): decompose this
        # request's wall into an EXACT partition — the accumulated stage
        # walls are clamped into their windows so the stages always sum to
        # the measured total (residuals land in prefill_queue / decode,
        # which is honest: unattributed time is queueing).
        fin_ts = time.time()
        admitted = req.admitted_at or req.created_at
        admit_wait = max(0.0, admitted - req.created_at)
        ft = s.first_token_at or admitted
        pf_window = max(0.0, ft - admitted)
        pf_compute = min(max(0.0, s.prefill_compute_s), pf_window)
        dec_window = max(0.0, fin_ts - ft)
        preempt = min(max(0.0, s.preempted_s), dec_window)
        stall = min(max(0.0, s.stall_s), dec_window - preempt)
        shed = max(0.0, req.shed_wait_s)
        stages = {
            "admit_wait": admit_wait,
            "shed": shed,
            "prefill_queue": pf_window - pf_compute,
            "prefill_compute": pf_compute,
            "decode": dec_window - preempt - stall,
            "stall": stall,
            "preempt": preempt,
        }
        total_s = admit_wait + shed + pf_window + dec_window
        tid = self._tid(req)
        self._waterfall.observe(
            stages, total_s, trace_id=tid, rid=req.request_id[:8],
            ts=req.created_at,
        )
        self._flight.event(
            "wf", trace_id=tid, request_id=req.request_id[:8],
            total_ms=round(total_s * 1e3, 2),
            **{f"{k}_ms": round(v * 1e3, 2) for k, v in stages.items()},
        )
        # Workload capture: one compact record per finished admitted
        # request — prefix-chain head hashes (routing/prefix.py digests),
        # never raw text; raw token ids only behind TPU_WORKLOAD_IDS=1.
        if self._workload.enabled():
            chain = prefix_fp.chain_hashes(
                req.prompt_ids, self._paging.block_tokens
            )[: workload.CHAIN_HEAD]
            self._workload.record(
                ts=req.created_at, rid=req.request_id, trace_id=tid,
                model=self.cfg.name, prompt_tokens=len(req.prompt_ids),
                chain=chain, max_tokens=req.max_tokens,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, output_tokens=s.generated, finish=finish,
                ids=req.prompt_ids, shed_s=shed,
            )
            self._flight.event(
                "wl", trace_id=tid, request_id=req.request_id[:8],
                prompt_tokens=len(req.prompt_ids),
                output_tokens=s.generated, finish=finish,
            )
        req.out.put(
            {
                "type": "done",
                "finish_reason": finish,
                "usage": {
                    "prompt_tokens": s.prompt_len,
                    "completion_tokens": s.generated,
                    "total_tokens": s.prompt_len + s.generated,
                },
                "ttft_ms": ttft_ms,
            }
        )
        req.out.put(_DONE)
        # identity-guarded: the fast-scan may have freed the entry
        # already, and admission may have re-filled it with a NEW
        # request whose slot state must not be clobbered
        if self._slots[slot_idx] is s:
            self._free_now(slot_idx)


# -- multi-host spelling ----------------------------------------------------
# (Folded in from the retired executor/slice_engine.py shim: one loop, one
# queue, one request dataclass — the multi-host behavior lives entirely in
# the GSPMDBackend dispatch seam, and SliceEngine is just the constructor
# that wires it.)

# The slice request type was always structurally identical to the engine's;
# now it IS the engine's.
SliceRequest = GenRequest


class SliceEngine(GenerationEngine):
    """`GenerationEngine` over a `GSPMDBackend` — the multi-host spelling of
    the one unified engine. Construct it in EVERY process of the cluster
    with identical arguments; `.start()` on the leader (process 0),
    `.run_follower()` everywhere else — both inherited. Keeps the old
    keyword surface (`cmd_addr`, `connect_timeout_s`, the strict
    quant-with-checkpoint error, the `max_slots % dp` check)."""

    def __init__(
        self,
        model: str | ModelConfig = "tiny-llm",
        *,
        mesh: Any,
        cmd_addr: str,
        max_slots: int = 8,
        max_seq_len: int = 256,
        dtype: Any = jnp.bfloat16,
        decode_chunk: int = 8,
        quant: str = "",
        weights_dir: str = "",
        tokenizer: Tokenizer | None = None,
        seed: int = 0,
        connect_timeout_s: float = 60.0,
        prefill_chunk: int = 0,
        target_ttft_ms: float = 2000.0,
        **engine_kw: Any,
    ):
        if quant not in ("", "int8") and weights_dir:
            # The unified engine downgrades unknown quant modes to a warning;
            # a multi-host boot must not silently serve different bytes than
            # the operator asked for across a whole slice.
            raise NotImplementedError(
                f"slice engine quant={quant!r} with a checkpoint "
                f"(only 'int8' is supported)"
            )
        if mesh is not None:
            dp = dict(mesh.shape).get("dp", 1)
            if max_slots % max(dp, 1) != 0:
                raise ValueError(
                    f"max_slots {max_slots} must divide over dp={dp}"
                )
        super().__init__(
            model,
            mesh=mesh,
            backend=GSPMDBackend(cmd_addr, connect_timeout_s=connect_timeout_s),
            max_slots=max_slots,
            max_seq_len=max_seq_len,
            dtype=dtype,
            decode_chunk=decode_chunk,
            quant=quant,
            weights_dir=weights_dir,
            tokenizer=tokenizer,
            seed=seed,
            prefill_chunk=prefill_chunk,
            target_ttft_ms=target_ttft_ms,
            **engine_kw,
        )
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        self.is_leader = self.process_index == 0
