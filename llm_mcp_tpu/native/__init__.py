"""ctypes loader for the framework's native (C++) components.

`load_bpe()` returns the compiled `libbpe` handle, building it from
`native/bpe_tokenizer.cpp` on first use (g++ is in the base image; pybind11
is not, hence the plain C ABI + ctypes). Builds are cached in
`native/build/` next to the source; set `LLM_MCP_TPU_NO_NATIVE=1` to force
the pure-Python fallbacks everywhere (CI images without a toolchain).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger("native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "bpe_tokenizer.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "build", "libbpe.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_failed = False


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    # compile to a per-process temp name, then atomically rename: concurrent
    # processes (core + worker on a shared volume, parallel test workers)
    # must never load a half-written .so
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-Wall", "-std=c++17", "-fPIC", "-shared", "-o", tmp, _SRC]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native build failed to run: %s", e)
        return False
    if r.returncode != 0:
        log.warning("native build failed:\n%s", r.stderr[-2000:])
        return False
    try:
        os.replace(tmp, _SO)
    except OSError as e:
        log.warning("native build rename failed: %s", e)
        return False
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p, i32p = ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32)
    lib.bpe_new.restype = ctypes.c_void_p
    lib.bpe_free.argtypes = [ctypes.c_void_p]
    lib.bpe_add_token.argtypes = [ctypes.c_void_p, u8p, ctypes.c_int, ctypes.c_int32]
    lib.bpe_add_token.restype = ctypes.c_int
    lib.bpe_add_merge.argtypes = [ctypes.c_void_p] + [ctypes.c_int32] * 4
    lib.bpe_add_merge.restype = ctypes.c_int
    lib.bpe_num_tokens.argtypes = [ctypes.c_void_p]
    lib.bpe_num_tokens.restype = ctypes.c_int
    lib.bpe_encode.argtypes = [ctypes.c_void_p, u8p, ctypes.c_int, i32p, ctypes.c_int]
    lib.bpe_encode.restype = ctypes.c_int
    lib.bpe_encode_batch.argtypes = [
        ctypes.c_void_p, u8p, i32p, ctypes.c_int, i32p, ctypes.c_int
    ]
    lib.bpe_encode_batch.restype = ctypes.c_int
    lib.bpe_decode.argtypes = [ctypes.c_void_p, i32p, ctypes.c_int, u8p, ctypes.c_int]
    lib.bpe_decode.restype = ctypes.c_int
    lib.utf8_hold.argtypes = [u8p, ctypes.c_int]
    lib.utf8_hold.restype = ctypes.c_int
    return lib


def load_bpe() -> ctypes.CDLL | None:
    """The libbpe handle, or None when native code is unavailable."""
    global _lib, _failed
    if _lib is not None:
        return _lib
    if _failed or os.environ.get("LLM_MCP_TPU_NO_NATIVE", "") in ("1", "true"):
        return None
    with _lock:
        if _lib is not None or _failed:
            return _lib
        needs_build = not os.path.exists(_SO) or (
            os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        )
        if needs_build and not _build():
            _failed = True
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError as e:
            log.warning("failed to load %s: %s", _SO, e)
            _failed = True
            return None
    return _lib
