"""Discovery subsystem: find and catalog every schedulable inference node.

Parity: reference `core/internal/discovery/` (discovery.go 914 LoC +
offline_handler.go). The reference shells out to `tailscale status --json`
and probes Ollama `/api/tags` per port; here the mesh sources are
TPU-native: GCE/TPU-VM metadata enumeration, static executor endpoints, and
an optional LAN subnet sweep — all probed over the same HTTP surface our
core/executor nodes serve (`/health`, `/v1/models`).
"""

from .probe import ProbeResult, probe_endpoint
from .runner import Runner
from .slices import enumerate_tpu_slice, parse_static_endpoints
from .subnet import scan_subnets

__all__ = [
    "Runner",
    "ProbeResult",
    "probe_endpoint",
    "enumerate_tpu_slice",
    "parse_static_endpoints",
    "scan_subnets",
]
