"""TPU-VM slice enumeration: metadata server + static endpoint config.

The reference's mesh source is `tailscale status --json` (discovery.go:88)
plus `OLLAMA_EXTRA_ENDPOINTS` static probes (discovery.go:388-425). The
TPU-native mesh sources are:

1. The GCE/TPU-VM metadata server: a multi-host TPU slice publishes its
   worker hostnames under `instance/attributes/worker-network-endpoints`
   (and `tpu-env` with ACCELERATOR_TYPE etc.), so every worker can
   enumerate its peers without any external binary.
2. `TPU_EXTRA_ENDPOINTS` — comma-separated `name=host:port` or `host:port`
   entries for static peers (K8s services, fixed VMs) — direct parity with
   OLLAMA_EXTRA_ENDPOINTS.
"""

from __future__ import annotations

import json
import socket
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any

METADATA_BASE = "http://metadata.google.internal/computeMetadata/v1"
METADATA_TIMEOUT_S = 1.0


@dataclass
class SliceInfo:
    """One TPU slice as seen from metadata: peer workers + topology."""

    accelerator_type: str = ""  # e.g. "v5litepod-8"
    worker_id: int = 0
    hostnames: list[str] = field(default_factory=list)  # peer worker hosts
    attributes: dict[str, Any] = field(default_factory=dict)


def _metadata_get(path: str, http_get=None) -> str | None:
    url = f"{METADATA_BASE}/{path}"
    if http_get is not None:
        try:
            status, body = http_get(url, METADATA_TIMEOUT_S, "")
            return body.decode("utf-8", "replace") if status == 200 else None
        except Exception:
            return None
    req = urllib.request.Request(url, headers={"Metadata-Flavor": "Google"})
    try:
        with urllib.request.urlopen(req, timeout=METADATA_TIMEOUT_S) as r:  # noqa: S310
            return r.read().decode("utf-8", "replace")
    except (urllib.error.URLError, socket.timeout, OSError):
        return None


def _parse_tpu_env(text: str) -> dict[str, str]:
    """tpu-env metadata is 'KEY: value' lines (YAML-ish flat map)."""
    out: dict[str, str] = {}
    for line in text.splitlines():
        k, sep, v = line.partition(":")
        if sep:
            out[k.strip()] = v.strip().strip("'\"")
    return out


def enumerate_tpu_slice(http_get=None) -> SliceInfo | None:
    """Enumerate this TPU slice's workers from the metadata server.

    Returns None when not on a TPU VM (metadata unreachable) — callers fall
    back to static endpoints, exactly like the reference degrades when the
    tailscale binary is absent (discovery.go:88-97 error path).
    """
    env_text = _metadata_get("instance/attributes/tpu-env", http_get)
    if env_text is None:
        return None
    env = _parse_tpu_env(env_text)
    info = SliceInfo(
        accelerator_type=env.get("ACCELERATOR_TYPE", ""),
        attributes=dict(env),
    )
    try:
        info.worker_id = int(env.get("WORKER_ID", "0") or 0)
    except ValueError:
        info.worker_id = 0
    # worker-network-endpoints: "ip:port:hostname,..." or hostnames CSV
    eps = _metadata_get("instance/attributes/worker-network-endpoints", http_get)
    hosts: list[str] = []
    if eps:
        for entry in eps.replace("\n", ",").split(","):
            entry = entry.strip()
            if not entry:
                continue
            # formats seen in the wild: "host", "ip", "ip:8470:host" — the
            # probe target is always the leading addr; the trailing hostname
            # (when present) only matters for vhost Host headers, which the
            # runner derives from the device name.
            hosts.append(entry.split(":")[0])
    elif env.get("WORKER_HOSTNAMES"):
        hosts = [h.strip() for h in env["WORKER_HOSTNAMES"].split(",") if h.strip()]
    info.hostnames = hosts
    return info


@dataclass
class StaticEndpoint:
    name: str
    host: str
    port: int


def parse_static_endpoints(spec: str, default_port: int = 8080) -> list[StaticEndpoint]:
    """Parse TPU_EXTRA_ENDPOINTS: "name=host:port,host2:port2,host3".

    Parity with OLLAMA_EXTRA_ENDPOINTS parsing (discovery.go:140-148): each
    entry is an optional name, a host, and an optional port.
    """
    out: list[StaticEndpoint] = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, rest = entry.partition("=")
        if not sep:
            name, rest = "", entry
        rest = rest.strip()
        host, port = rest, default_port
        if rest.startswith("["):  # [v6]:port
            closing = rest.find("]")
            host = rest[1:closing] if closing > 0 else rest.strip("[]")
            tail = rest[closing + 1 :] if closing > 0 else ""
            if tail.startswith(":"):
                try:
                    port = int(tail[1:])
                except ValueError:
                    port = default_port
        elif rest.count(":") == 1:
            h, _, p = rest.partition(":")
            host = h
            try:
                port = int(p)
            except ValueError:
                port = default_port
        out.append(StaticEndpoint(name=name or host, host=host, port=port))
    return out


def slice_device_tags(info: SliceInfo) -> dict[str, Any]:
    """Catalog tags for a slice-discovered device (cf. discovery.go:200-246
    tagging mesh nodes with os/online/addresses metadata)."""
    return {
        "tpu": True,
        "source": "tpu-metadata",
        "accelerator_type": info.accelerator_type,
        "worker_id": info.worker_id,
        "workers": len(info.hostnames),
    }


def parse_worker_network_endpoints_json(text: str) -> list[str]:
    """Some TPU runtimes publish endpoints as JSON; accept both shapes."""
    try:
        doc = json.loads(text)
    except ValueError:
        return []
    hosts: list[str] = []
    if isinstance(doc, list):
        for item in doc:
            if isinstance(item, str):
                hosts.append(item.split(":")[0])
            elif isinstance(item, dict):
                h = item.get("ipAddress") or item.get("host") or item.get("hostname")
                if h:
                    hosts.append(str(h))
    return hosts
