"""Optional concurrent LAN subnet sweep for inference nodes.

Parity: reference `discovery.go:669-814` — 24 concurrent scanners, 300 ms
per-probe timeout, private-IPv4-only guard, ≤512 addresses per prefix. The
sweep looks for our node surface (`/health`) instead of Ollama.
"""

from __future__ import annotations

import ipaddress
import queue as _queue
import threading
from dataclasses import dataclass
from typing import Callable

from .probe import HttpGet, probe_endpoint

SCAN_WORKERS = 24  # discovery.go:688
SCAN_TIMEOUT_S = 0.3  # discovery.go:691 (300 ms)
MAX_ADDRS_PER_PREFIX = 512  # discovery.go:676


@dataclass
class ScanHit:
    addr: str
    port: int
    latency_ms: float


def iter_scan_addrs(subnets: list[str]) -> list[str]:
    """Expand subnet specs to concrete host addresses with the reference's
    guards: private IPv4 only, ≤512 hosts per prefix, skip net/bcast."""
    out: list[str] = []
    for spec in subnets:
        spec = spec.strip()
        if not spec:
            continue
        try:
            net = ipaddress.ip_network(spec, strict=False)
        except ValueError:
            continue
        if net.version != 4 or not net.is_private:
            continue
        count = 0
        for host in net.hosts():
            if count >= MAX_ADDRS_PER_PREFIX:
                break
            out.append(str(host))
            count += 1
    return out


def scan_subnets(
    subnets: list[str],
    ports: list[int],
    *,
    timeout: float = SCAN_TIMEOUT_S,
    workers: int = SCAN_WORKERS,
    http_get: HttpGet | None = None,
    on_hit: Callable[[ScanHit], None] | None = None,
) -> list[ScanHit]:
    """Sweep subnets × ports concurrently; return endpoints that answered.

    WaitGroup-coordinated worker pool in the reference (discovery.go:688-758)
    becomes a thread pool draining a work queue here.
    """
    addrs = iter_scan_addrs(subnets)
    work: _queue.Queue[tuple[str, int]] = _queue.Queue()
    for a in addrs:
        for p in ports:
            work.put((a, p))
    hits: list[ScanHit] = []
    lock = threading.Lock()

    def _worker() -> None:
        while True:
            try:
                addr, port = work.get_nowait()
            except _queue.Empty:
                return
            res = probe_endpoint(
                [addr], port, timeout=timeout, http_get=http_get, fetch_models=False
            )
            if res.ok:
                hit = ScanHit(addr=addr, port=port, latency_ms=res.latency_ms)
                with lock:
                    hits.append(hit)
                if on_hit:
                    on_hit(hit)
            work.task_done()

    threads = [
        threading.Thread(target=_worker, name=f"subnet-scan-{i}", daemon=True)
        for i in range(min(workers, max(1, work.qsize())))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return hits
