"""Discovery runner: the periodic orchestration of all mesh sources.

Parity: reference `discovery.go:79-170` (Runner.Run walking tailscale nodes,
probing, syncing catalogs, collecting offline devices) and
`offline_handler.go:12-38` (requeue running jobs of offline devices). Mesh
sources here: TPU-slice metadata peers, static TPU_EXTRA_ENDPOINTS, optional
subnet sweep, plus the in-process local device (self-registration hook).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ..routing.limits import LimitsEngine
from ..state.catalog import Catalog, infer_model_meta
from ..state.queue import JobQueue
from ..utils.config import Config
from .probe import HttpGet, ProbeResult, probe_endpoint
from .slices import (
    StaticEndpoint,
    enumerate_tpu_slice,
    parse_static_endpoints,
    slice_device_tags,
)
from .subnet import scan_subnets

log = logging.getLogger("discovery")


@dataclass
class RunResult:
    devices_seen: int = 0
    devices_online: int = 0
    devices_offline: int = 0
    vanished: list[str] = field(default_factory=list)
    models_synced: int = 0
    jobs_requeued: int = 0
    duration_ms: float = 0.0
    sources: dict[str, int] = field(default_factory=dict)
    errors: list[str] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return {
            "devices_seen": self.devices_seen,
            "devices_online": self.devices_online,
            "devices_offline": self.devices_offline,
            "models_synced": self.models_synced,
            "jobs_requeued": self.jobs_requeued,
            "duration_ms": round(self.duration_ms, 1),
            "sources": self.sources,
            "errors": self.errors,
        }


class Runner:
    """Walks every mesh source, upserts devices + model catalogs, marks
    vanished devices offline and requeues their running jobs."""

    def __init__(
        self,
        catalog: Catalog,
        queue: JobQueue,
        *,
        limits: LimitsEngine | None = None,
        cfg: Config | None = None,
        http_get: HttpGet | None = None,
        register_local: Callable[[], None] | None = None,
        ports: list[int] | None = None,
        self_device_id: str = "",
    ):
        self.catalog = catalog
        self.queue = queue
        self.limits = limits
        self.cfg = cfg or Config()
        self.http_get = http_get
        self.register_local = register_local
        # Probed peers reporting this id in /health are this very process —
        # skip them so the local node isn't cataloged twice (once self-
        # registered, once as a phantom probed device).
        self.self_device_id = self_device_id
        # Multi-port probing: one host can expose several executor processes,
        # each becoming its own schedulable child device — the reference's
        # OLLAMA_PORTS port-device pattern (discovery.go:249-280).
        self.ports = ports or [8080]
        self._lock = threading.Lock()
        self.last_run: RunResult | None = None
        self.last_run_at: float = 0.0

    # -- public ------------------------------------------------------------

    def run(self) -> dict[str, Any]:
        t0 = time.monotonic()
        res = RunResult()
        seen: set[str] = set()

        if self.register_local is not None:
            try:
                self.register_local()
                res.sources["local"] = 1
            except Exception as e:  # local registration is best-effort
                res.errors.append(f"local: {e}")

        self._run_tpu_slice(res, seen)
        self._run_static_endpoints(res, seen)
        if self.cfg.discovery_scan_subnets and self.cfg.discovery_subnets:
            self._run_subnet_scan(res, seen)

        res.jobs_requeued = self._handle_offline(res, seen)
        if self.limits is not None:
            try:
                # Re-derive HBM-based limits from fresh device tags; operator
                # presets always win inside apply_specs (limits.go:83-102).
                self.limits.apply_specs()
            except Exception as e:
                res.errors.append(f"limits: {e}")
        res.devices_online = len(self.catalog.list_devices(online_only=True))
        res.duration_ms = (time.monotonic() - t0) * 1000.0
        with self._lock:
            self.last_run = res
            self.last_run_at = time.time()
        log.info(
            "discovery run: %d seen, %d online, %d requeued, %.0fms",
            res.devices_seen,
            res.devices_online,
            res.jobs_requeued,
            res.duration_ms,
        )
        return res.to_dict()

    # -- sources -----------------------------------------------------------

    def _run_tpu_slice(self, res: RunResult, seen: set[str]) -> None:
        info = enumerate_tpu_slice(self.http_get)
        if info is None:
            return
        count = 0
        for host in info.hostnames:
            for port in self.ports:
                did = self._probe_and_upsert(
                    device_id=f"{host}:{port}",
                    name=host,
                    addrs=[host],
                    port=port,
                    base_tags={**slice_device_tags(info), "base_device": host},
                    res=res,
                )
                if did:
                    seen.add(did)
                    count += 1
        res.sources["tpu-slice"] = count

    def _run_static_endpoints(self, res: RunResult, seen: set[str]) -> None:
        eps: list[StaticEndpoint] = parse_static_endpoints(
            self.cfg.tpu_extra_endpoints, default_port=self.ports[0]
        )
        count = 0
        for ep in eps:
            did = self._probe_and_upsert(
                device_id=f"{ep.host}:{ep.port}",
                name=ep.name,
                addrs=[ep.host],
                port=ep.port,
                base_tags={"source": "static", "endpoint": ep.name},
                res=res,
            )
            if did:
                seen.add(did)
                count += 1
        res.sources["static"] = count

    def _run_subnet_scan(self, res: RunResult, seen: set[str]) -> None:
        subnets = [s for s in self.cfg.discovery_subnets.split(",") if s.strip()]
        hits = scan_subnets(subnets, self.ports, http_get=self.http_get)
        count = 0
        for hit in hits:
            did = self._probe_and_upsert(
                device_id=f"{hit.addr}:{hit.port}",
                name=hit.addr,
                addrs=[hit.addr],
                port=hit.port,
                base_tags={"source": "subnet-scan"},
                res=res,
            )
            if did:
                seen.add(did)
                count += 1
        res.sources["subnet"] = count

    # -- device + catalog upsert -------------------------------------------

    def _probe_and_upsert(
        self,
        *,
        device_id: str,
        name: str,
        addrs: list[str],
        port: int,
        base_tags: dict[str, Any],
        res: RunResult,
    ) -> str | None:
        """Probe one endpoint; on success upsert the device, its models, and
        HBM-derived limits. Returns the device id if it answered."""
        probe: ProbeResult = probe_endpoint(
            addrs, port, http_get=self.http_get, host_header=name
        )
        res.devices_seen += 1
        if probe.ok and self.self_device_id and probe.info.get("device_id") == self.self_device_id:
            return None  # that's us — the self-registered device is authoritative
        if not probe.ok:
            existing = self.catalog.get_device(device_id)
            if existing is not None and existing.get("online"):
                self.catalog.set_device_online(device_id, False)
                res.devices_offline += 1
                res.vanished.append(device_id)
            return None
        tags = {
            **base_tags,
            "addr": probe.addr,
            "port": port,
            "latency_ms": probe.latency_ms,
            "probes": probe.probes,
        }
        # Surface executor identity from /health (chips, platform, hbm),
        # plus the prefix tier's dynamic fields: the peer's resident-chain
        # digest (route-time locality scoring + boot warm-fill ranking)
        # and its PrefixFetch gRPC address.
        for key in ("platform", "chips", "hbm_gb", "service",
                    "prefix_digest", "transfer_addr"):
            if key in probe.info:
                tags[key] = probe.info[key]
        self.catalog.upsert_device(
            device_id, name=name, addr=f"{probe.addr}:{port}", online=True, tags=tags
        )
        res.models_synced += self._sync_models(device_id, probe)
        return device_id

    def _sync_models(self, device_id: str, probe: ProbeResult) -> int:
        """Upsert probed models with name-inferred metadata and bind them to
        the device; parity with syncDeviceModels (discovery.go:482-624):
        models missing from this probe become unavailable on the device."""
        n = 0
        for meta in probe.model_meta:
            mid = str(meta.get("id") or meta.get("name") or "")
            if not mid:
                continue
            inferred = infer_model_meta(mid, float(meta.get("params_b") or 0.0))
            self.catalog.upsert_model(
                mid,
                kind=str(meta.get("kind") or inferred["kind"]),
                tier=str(meta.get("tier") or inferred["tier"]),
                thinking=bool(meta.get("thinking", inferred["thinking"])),
                context_k=int(meta.get("context_k") or inferred["context_k"]),
                params_b=float(meta.get("params_b") or inferred["params_b"]),
            )
            n += 1
        self.catalog.sync_device_models(device_id, probe.models)
        return n

    # -- offline propagation ------------------------------------------------

    def _handle_offline(self, res: RunResult, seen: set[str]) -> int:
        """Mark discovered-before-but-not-seen devices offline and reset
        leases of their running jobs so they requeue immediately
        (offline_handler.go:12-38)."""
        offline_ids: list[str] = list(res.vanished)
        for dev in self.catalog.list_devices(online_only=True):
            did = dev["id"]
            tags = dev.get("tags") or {}
            if tags.get("self"):
                continue  # the in-process device is authoritative about itself
            if tags.get("source") in (None, "local"):
                continue
            if did not in seen:
                self.catalog.set_device_online(did, False)
                offline_ids.append(did)
                res.devices_offline += 1
        if not offline_ids:
            return 0
        return self.queue.requeue_device_jobs(offline_ids)

    # -- background loop ----------------------------------------------------

    def start_background(self, stop: threading.Event) -> threading.Thread:
        """Periodic runner thread (reference main.go:101-112 ticker)."""

        def _loop() -> None:
            while not stop.is_set():
                try:
                    self.run()
                except Exception:
                    log.exception("discovery run failed")
                stop.wait(max(5, self.cfg.discovery_interval_s))

        t = threading.Thread(target=_loop, name="discovery", daemon=True)
        t.start()
        return t
