"""HTTP probing of candidate inference endpoints.

Parity: reference `discovery.go:283-384` (probeOllamaPort — per-addr probe
with latency measurement, best-addr selection, Host-header retry for
IP-based access to named vhosts) and `discovery.go:388-425`
(probeExtraEndpoint). The probe target here is our own node surface:
`GET /health` for liveness + identity, `GET /v1/models` for the loaded model
list — the TPU-native analog of Ollama `GET /api/tags`.
"""

from __future__ import annotations

import json
import socket
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable

DEFAULT_TIMEOUT_S = 2.0  # reference probe timeout: discovery.go:284


@dataclass
class ProbeResult:
    ok: bool = False
    addr: str = ""  # the address that answered fastest
    latency_ms: float = 0.0
    models: list[str] = field(default_factory=list)
    model_meta: list[dict[str, Any]] = field(default_factory=list)
    info: dict[str, Any] = field(default_factory=dict)  # /health body
    probes: list[dict[str, Any]] = field(default_factory=list)  # per-addr log
    error: str = ""


def _default_http_get(url: str, timeout: float, host_header: str = "") -> tuple[int, bytes]:
    req = urllib.request.Request(url, method="GET")
    if host_header:
        req.add_header("Host", host_header)
    with urllib.request.urlopen(req, timeout=timeout) as r:  # noqa: S310
        return r.status, r.read()


HttpGet = Callable[..., tuple[int, bytes]]


def _try_addr(
    addr: str,
    port: int,
    timeout: float,
    http_get: HttpGet,
    host_header: str = "",
) -> tuple[dict[str, Any] | None, float, str]:
    """One candidate address: hit /health, return (health_body, ms, err)."""
    base = f"http://{_bracket(addr)}:{port}"
    t0 = time.monotonic()
    try:
        status, body = http_get(f"{base}/health", timeout, host_header)
        ms = (time.monotonic() - t0) * 1000.0
        if status != 200:
            return None, ms, f"status {status}"
        try:
            info = json.loads(body.decode("utf-8", "replace"))
        except (ValueError, UnicodeDecodeError):
            info = {}
        if not isinstance(info, dict):
            info = {}
        return info, ms, ""
    except (urllib.error.URLError, socket.timeout, OSError, ValueError) as e:
        return None, (time.monotonic() - t0) * 1000.0, str(e)


def _bracket(addr: str) -> str:
    """IPv6 literals need brackets in URLs (reference main.py:141-160)."""
    if ":" in addr and not addr.startswith("["):
        return f"[{addr}]"
    return addr


def probe_endpoint(
    addrs: list[str],
    port: int,
    *,
    timeout: float = DEFAULT_TIMEOUT_S,
    host_header: str = "",
    http_get: HttpGet | None = None,
    fetch_models: bool = True,
) -> ProbeResult:
    """Probe every candidate address of one endpoint, pick the fastest.

    Mirrors the reference's best-addr-by-latency selection with per-addr
    probe logging (`discovery.go:283-384`): all candidate addrs are tried,
    each gets a {addr, ok, latency_ms, error} record, and the fastest
    healthy one becomes the device's canonical address.
    """
    http_get = http_get or _default_http_get
    res = ProbeResult()
    best_ms = float("inf")
    best_info: dict[str, Any] = {}
    for addr in addrs:
        if not addr:
            continue
        info, ms, err = _try_addr(addr, port, timeout, http_get)
        if info is None and host_header:
            # IP-based access to a named vhost: retry with Host header
            # (reference discovery.go:460-479).
            info, ms, err = _try_addr(addr, port, timeout, http_get, host_header)
        res.probes.append(
            {"addr": addr, "ok": info is not None, "latency_ms": round(ms, 1), "error": err}
        )
        if info is not None and ms < best_ms:
            best_ms, best_info, res.addr = ms, info, addr
    if not res.addr:
        res.error = "; ".join(p["error"] for p in res.probes if p["error"]) or "no addrs"
        return res
    res.ok = True
    res.latency_ms = round(best_ms, 1)
    res.info = best_info

    if fetch_models:
        # The device's truly-loaded models are its /health `engines` list —
        # the analog of Ollama /api/tags listing locally present models.
        # /v1/models serves the peer's whole catalog (incl. cloud models and
        # other devices' models), so it is only used to ENRICH metadata for
        # engine ids, never to define what this device hosts.
        engines = probe_info_engines(res.info)
        meta_by_id: dict[str, dict[str, Any]] = {}
        base = f"http://{_bracket(res.addr)}:{port}"
        try:
            status, body = http_get(f"{base}/v1/models", timeout, host_header)
            if status == 200:
                doc = json.loads(body.decode("utf-8", "replace"))
                for m in doc.get("models", doc.get("data", [])) or []:
                    if isinstance(m, str):
                        meta_by_id[m] = {"id": m}
                    elif isinstance(m, dict) and (m.get("id") or m.get("name")):
                        mid = str(m.get("id") or m.get("name"))
                        meta_by_id[mid] = m
        except (urllib.error.URLError, socket.timeout, OSError, ValueError):
            pass  # healthy node with unreadable catalog still counts as online
        if engines is not None:
            res.models = engines
            res.model_meta = [meta_by_id.get(m, {"id": m}) for m in engines]
        else:
            # Pre-engines peer (or non-core endpoint): fall back to its
            # model listing wholesale.
            res.models = list(meta_by_id)
            res.model_meta = list(meta_by_id.values())
    return res


def probe_info_engines(info: dict[str, Any]) -> list[str] | None:
    """Extract the loaded-engine model list from a /health body, or None
    when the peer doesn't report one."""
    engines = info.get("engines")
    if isinstance(engines, list):
        return [str(e) for e in engines]
    return None
