"""Fault injection: deterministic, env-configurable failure seeding.

The reference has a rich failure-RECOVERY machinery (leases, retry budgets,
circuit breaker, offline propagation — SURVEY.md §5) but "fault injection:
none exists" is called out as a gap to close. This module closes it: any
subsystem can place a `maybe_fail("site")` probe on its hot path; operators
(and chaos tests) arm sites via one env var without touching code:

    FAULT_INJECT="worker.execute:0.3,engine.decode:0.05:delay=2"

Spec grammar (comma-separated):  site:probability[:key=value...]
  - probability in [0, 1] — chance each probe call trips
  - mode `delay=SECONDS` sleeps instead of raising (latency injection)
  - mode `error=MESSAGE` customizes the raised message

Draws come from a dedicated seeded RNG (`FAULT_SEED`, default 0) so chaos
runs are reproducible — the same seed trips the same calls. Probes are
no-ops (one dict lookup) when the site isn't armed; arming is read once at
first use and can be re-armed explicitly in tests via `configure()`.

Sites wired in-tree:
  worker.execute   — Executors.dispatch, before running any job kind
  worker.complete  — Worker.run_once, after execute / before reporting
                     (exercises lease-expiry reclaim: the job outcome is
                     computed but never reported, as if the worker died)
  engine.decode    — GenerationEngine decode loop (engine failure guards)
  api.request      — HTTP request dispatch (client-visible 5xx)
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Any

log = logging.getLogger("faults")


class FaultInjected(RuntimeError):
    """Raised by an armed probe. Deliberately a plain RuntimeError subclass:
    callers must survive it exactly as they would a real failure."""


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._sites: dict[str, dict[str, Any]] = {}
        self._rngs: dict[str, random.Random] = {}
        self._seed = 0
        self._loaded = False
        self.trips: dict[str, int] = {}

    def configure(self, spec: str | None = None, seed: int | None = None) -> None:
        """Parse FAULT_INJECT-style spec. Explicit call re-arms (tests);
        passing None re-reads the environment. Every parse error is
        log-and-ignore — a chaos-config typo must never become a NEW
        failure mode in the component under test."""
        with self._lock:
            raw = os.environ.get("FAULT_INJECT", "") if spec is None else spec
            if seed is None:
                try:
                    seed = int(os.environ.get("FAULT_SEED", "0") or 0)
                except ValueError:
                    log.warning("bad FAULT_SEED %r; using 0",
                                os.environ.get("FAULT_SEED"))
                    seed = 0
            self._seed = seed
            self._sites = {}
            self._rngs = {}
            self.trips = {}
            for part in (raw or "").split(","):
                part = part.strip()
                if not part:
                    continue
                fields = part.split(":")
                if len(fields) < 2:
                    log.warning("fault spec %r missing probability; ignored", part)
                    continue
                site = fields[0].strip()
                try:
                    prob = float(fields[1])
                except ValueError:
                    log.warning("fault spec %r has bad probability; ignored", part)
                    continue
                opts: dict[str, Any] = {}
                bad = False
                for f in fields[2:]:
                    k, _, v = f.partition("=")
                    k, v = k.strip(), v.strip()
                    if k == "delay":
                        try:
                            opts[k] = float(v)
                        except ValueError:
                            log.warning("fault spec %r has bad delay; ignored", part)
                            bad = True
                            break
                    else:
                        opts[k] = v
                if bad:
                    continue
                self._sites[site] = {"prob": max(0.0, min(1.0, prob)), **opts}
                # per-site RNG: each site's trip sequence depends only on its
                # own call count, so multi-site / multi-threaded runs stay
                # reproducible per site under the same seed
                # string seeding is stable across processes (unlike hash())
                self._rngs[site] = random.Random(f"{seed}:{site}")
                log.warning("fault injection ARMED: %s p=%.2f %s", site, prob, opts)
            self._loaded = True

    def maybe_fail(self, site: str, detail: str = "") -> None:
        if not self._loaded:
            self.configure()
        # unarmed fast path: no lock — probes on hot paths (engine decode,
        # HTTP dispatch) must stay a single dict lookup when injection is off
        # (dict reads are atomic under the GIL; configure swaps whole entries)
        if site not in self._sites:
            return
        # read the site config and its RNG under ONE lock acquisition: a
        # concurrent configure() may swap both, and a half-read (cfg from the
        # old map, missing rng in the new one) must disarm, not KeyError in
        # the probed hot path
        with self._lock:
            cfg = self._sites.get(site)
            rng = self._rngs.get(site)
            if not cfg or rng is None:
                return
            trip = rng.random() < cfg["prob"]
            if trip:
                self.trips[site] = self.trips.get(site, 0) + 1
        if not trip:
            return
        if "delay" in cfg:
            d = cfg["delay"]
            log.warning("fault injected at %s: delay %.2fs %s", site, d, detail)
            time.sleep(d)
            return
        msg = cfg.get("error") or f"injected fault at {site}"
        log.warning("fault injected at %s: %s %s", site, msg, detail)
        raise FaultInjected(msg)

    def armed(self, site: str) -> bool:
        if not self._loaded:
            self.configure()
        return site in self._sites


_registry = _Registry()

configure = _registry.configure
maybe_fail = _registry.maybe_fail
armed = _registry.armed


def trip_counts() -> dict[str, int]:
    return dict(_registry.trips)
