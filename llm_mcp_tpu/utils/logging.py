"""Structured JSON logging.

Parity: the reference installs a `slog` JSON handler at process start
(`core/cmd/core/main.go:27`) and logs method/path and routing decisions with
correlated ids (`handlers.go:31`, `router.go:272,526`). Same idea here:
one-line JSON records with a stable key set, on stderr.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out: dict[str, Any] = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        extra = getattr(record, "kv", None)
        if isinstance(extra, dict):
            out.update(extra)
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False, default=str)


def setup_logging(level: int = logging.INFO) -> None:
    root = logging.getLogger()
    root.setLevel(level)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(JSONFormatter())
    root.addHandler(handler)


def kv(logger: logging.Logger, level: int, msg: str, **fields: Any) -> None:
    """Log `msg` with structured key/value fields."""
    logger.log(level, msg, extra={"kv": fields})
