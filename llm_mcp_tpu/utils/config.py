"""Env-var configuration helpers.

Parity: reference `core/internal/config/config.go:9-34` (Getenv/GetenvInt and
provider key presence checks). The reference uses pure env-var config with no
flag library; we keep that model and add typed helpers plus a `Config` snapshot
object so services can be constructed hermetically in tests.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def getenv(key: str, default: str = "") -> str:
    v = os.environ.get(key, "")
    return v if v != "" else default


def getenv_int(key: str, default: int) -> int:
    v = os.environ.get(key, "")
    try:
        return int(v)
    except (TypeError, ValueError):
        return default


def getenv_float(key: str, default: float) -> float:
    v = os.environ.get(key, "")
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def getenv_bool(key: str, default: bool = False) -> bool:
    v = os.environ.get(key, "").strip().lower()
    if v in ("1", "true", "yes", "on"):
        return True
    if v in ("0", "false", "no", "off"):
        return False
    return default


@dataclass
class Config:
    """Snapshot of all service configuration.

    Mirrors the env catalog of the reference (`compose.yml:26-42`,
    `doc/README.md` env section) with TPU-specific additions.
    """

    # Core service
    http_addr: str = field(default_factory=lambda: getenv("CORE_HTTP_ADDR", ":8080"))
    grpc_addr: str = field(default_factory=lambda: getenv("CORE_GRPC_ADDR", ":9090"))
    db_path: str = field(default_factory=lambda: getenv("DB_PATH", "llmmcp.sqlite3"))

    # Discovery
    discovery_interval_s: int = field(default_factory=lambda: getenv_int("DISCOVERY_INTERVAL", 60))
    tpu_extra_endpoints: str = field(default_factory=lambda: getenv("TPU_EXTRA_ENDPOINTS", ""))
    discovery_scan_subnets: bool = field(default_factory=lambda: getenv_bool("DISCOVERY_SCAN_SUBNETS"))
    discovery_subnets: str = field(default_factory=lambda: getenv("DISCOVERY_SUBNETS", ""))

    # Scheduling / limits
    device_max_concurrency: int = field(default_factory=lambda: getenv_int("DEVICE_MAX_CONCURRENCY", 2))
    strict_model_limits: bool = field(default_factory=lambda: getenv_bool("STRICT_MODEL_LIMITS"))
    device_limits_json: str = field(default_factory=lambda: getenv("DEVICE_LIMITS_JSON", ""))
    device_limits_file: str = field(default_factory=lambda: getenv("DEVICE_LIMITS_FILE", ""))
    device_limits_interval_s: int = field(default_factory=lambda: getenv_int("DEVICE_LIMITS_INTERVAL", 300))
    # planner (background maintenance, see llm_mcp_tpu/planner.py) — the
    # reference documents these knobs for its absent planner/ module
    # (CHANGELOG_V2.md); 0 interval disables the loop entirely.
    planner_interval_s: int = field(default_factory=lambda: getenv_int("PLANNER_INTERVAL", 3600))
    planner_stale_days: float = field(default_factory=lambda: getenv_float("PLANNER_STALE_DAYS", 7.0))
    planner_max_price_per_1m: float = field(default_factory=lambda: getenv_float("PLANNER_MAX_PRICE_PER_1M", 0.0))
    planner_bench_max_age_s: float = field(default_factory=lambda: getenv_float("PLANNER_BENCH_MAX_AGE_S", 0.0))
    benchmark_max_price_per_1m: float = field(default_factory=lambda: getenv_float("BENCHMARK_MAX_PRICE_PER_1M", 10.0))

    # Worker
    worker_id: str = field(default_factory=lambda: getenv("WORKER_ID", ""))
    worker_name: str = field(default_factory=lambda: getenv("WORKER_NAME", ""))
    worker_kinds: str = field(default_factory=lambda: getenv("WORKER_KINDS", ""))
    worker_lease_seconds: int = field(default_factory=lambda: getenv_int("WORKER_LEASE_SECONDS", 30))

    # Cloud providers
    openai_api_key: str = field(default_factory=lambda: getenv("OPENAI_API_KEY", ""))
    openai_base_url: str = field(default_factory=lambda: getenv("OPENAI_BASE_URL", "https://api.openai.com/v1"))
    openrouter_api_key: str = field(default_factory=lambda: getenv("OPENROUTER_API_KEY", ""))
    openrouter_base_url: str = field(
        default_factory=lambda: getenv("OPENROUTER_BASE_URL", "https://openrouter.ai/api/v1")
    )
    cloud_embed_dimensions: int = field(default_factory=lambda: getenv_int("CLOUD_EMBED_DIMENSIONS", 0))

    # Knowledge services
    lightrag_url: str = field(default_factory=lambda: getenv("LIGHTRAG_URL", ""))
    lightrag_api_key: str = field(default_factory=lambda: getenv("LIGHTRAG_API_KEY", ""))
    mem0_url: str = field(default_factory=lambda: getenv("MEM0_URL", ""))

    # Telemetry
    telegram_bot_token: str = field(default_factory=lambda: getenv("TELEGRAM_BOT_TOKEN", ""))
    telegram_chat_id: str = field(default_factory=lambda: getenv("TELEGRAM_CHAT_ID", ""))
    telemetry_interval_s: int = field(default_factory=lambda: getenv_int("TELEMETRY_INTERVAL", 30))
    alert_fail_threshold: int = field(default_factory=lambda: getenv_int("ALERT_FAIL_THRESHOLD", 5))

    # TPU executor
    tpu_model: str = field(default_factory=lambda: getenv("TPU_MODEL", "llama-3.1-8b"))
    tpu_embed_model: str = field(default_factory=lambda: getenv("TPU_EMBED_MODEL", "nomic-embed-text"))
    # "" | int8 — 8B-class embedders (qwen3-embedding-8b) only fit 16 GB int8
    tpu_embed_quant: str = field(default_factory=lambda: getenv("TPU_EMBED_QUANT", ""))
    tpu_weights_dir: str = field(default_factory=lambda: getenv("TPU_WEIGHTS_DIR", ""))
    # the embed model's OWN checkpoint dir — a config.json beside weights is
    # authoritative per engine, so the generator's dir must never leak into
    # the embedder's config resolution (decoder-architecture embedders like
    # qwen3-embedding load real safetensors through this)
    tpu_embed_weights_dir: str = field(
        default_factory=lambda: getenv("TPU_EMBED_WEIGHTS_DIR", "")
    )
    # 32 fits the default llama-3.1-8b KV cache alongside its weights on one
    # chip; for 1B-class models TPU_MAX_SLOTS=64 is the measured throughput
    # optimum (bench.py sweep — larger hits an XLA full-cache-copy cliff).
    tpu_max_slots: int = field(default_factory=lambda: getenv_int("TPU_MAX_SLOTS", 32))
    tpu_max_seq_len: int = field(default_factory=lambda: getenv_int("TPU_MAX_SEQ_LEN", 2048))
    tpu_mesh_shape: str = field(default_factory=lambda: getenv("TPU_MESH_SHAPE", ""))  # e.g. "dp=1,tp=8"
    # multi-PROCESS serving (executor/engine.py SliceEngine): leader→follower
    # command channel address; non-empty + a jax.distributed triplet puts
    # process 0 in CoreServer as the slice leader, every other process in
    # the follower loop — the whole slice registers as ONE device
    tpu_slice_cmd_addr: str = field(default_factory=lambda: getenv("TPU_SLICE_CMD_ADDR", ""))
    tpu_quant: str = field(default_factory=lambda: getenv("TPU_QUANT", ""))  # "" | int8
    tpu_kv_quant: str = field(default_factory=lambda: getenv("TPU_KV_QUANT", ""))  # "" | int8
    # chunked prefill segment length (tokens); 0 disables interleaved prefill
    tpu_prefill_chunk: int = field(default_factory=lambda: getenv_int("TPU_PREFILL_CHUNK", 512))
    # token-budget scheduler TTFT target (ms): the per-round prefill token
    # budget is clamped so the oldest mid-prefill prompt activates within
    # this deadline (executor/scheduler.py). Replaces the retired
    # TPU_PREFILL_BOOST wall-clock multiplier (doc/performance.md).
    tpu_target_ttft_ms: float = field(default_factory=lambda: getenv_float("TPU_TARGET_TTFT_MS", 2000.0))
    # slot compaction: decode only active rows (auto | on | off)
    tpu_decode_compact: str = field(default_factory=lambda: getenv("TPU_DECODE_COMPACT", "auto"))
    # admission prompt buckets: fine (pow2 + 1.5x midpoints) | pow2
    tpu_prefill_buckets: str = field(default_factory=lambda: getenv("TPU_PREFILL_BUCKETS", "fine"))
    # prompt-prefix KV cache budget in MB (0 disables)
    tpu_prompt_cache_mb: int = field(default_factory=lambda: getenv_int("TPU_PROMPT_CACHE_MB", 256))
    # self-speculative decoding (executor/engine.py draft-and-verify):
    # TPU_SPEC=0 is the kill switch (byte-identical non-speculative decode
    # path); TPU_SPEC_K caps the drafts per verify call; TPU_SPEC_MIN_NGRAM
    # is the shortest suffix the prompt-lookup drafter matches on. The
    # engines read the env directly at construction (TPU_PIPELINE_DEPTH
    # pattern); these fields surface the knobs in config dumps.
    tpu_spec: bool = field(default_factory=lambda: getenv("TPU_SPEC", "1") != "0")
    tpu_spec_k: int = field(default_factory=lambda: getenv_int("TPU_SPEC_K", 7))
    tpu_spec_min_ngram: int = field(default_factory=lambda: getenv_int("TPU_SPEC_MIN_NGRAM", 2))
    # HBM-aware KV pool (executor/memory.py): TPU_KV_HOST_OFFLOAD=1 enables
    # slot preemption with host offload + watermark admission; default off is
    # a true no-op (the pool is never constructed — byte-identical scheduler
    # decisions vs the pool-less engine). TPU_ADMIT_WATERMARK is the offered
    # load multiple of max_slots above which the API sheds (429+Retry-After,
    # deferred job claims); TPU_PREEMPT_POLICY ∈ priority|idle|tokens|
    # slo_debt picks the eviction victim ordering (slo_debt prefers the
    # tenant with the most goodput surplus). Engines read the env directly at
    # construction (TPU_PIPELINE_DEPTH pattern); these fields surface the
    # knobs in config dumps.
    tpu_kv_host_offload: bool = field(default_factory=lambda: getenv_bool("TPU_KV_HOST_OFFLOAD"))
    tpu_admit_watermark: float = field(default_factory=lambda: getenv_float("TPU_ADMIT_WATERMARK", 1.5))
    tpu_preempt_policy: str = field(default_factory=lambda: getenv("TPU_PREEMPT_POLICY", "priority"))
    # extra local API ports for discovery probing (comma-separated; the
    # OLLAMA_PORTS pattern) — multiple executor processes on one host get
    # probed automatically instead of only the pinned self port
    tpu_extra_ports: str = field(default_factory=lambda: getenv("TPU_EXTRA_PORTS", ""))
    # model zoo (executor/zoo.py): TPU_ZOO_MODELS is a comma-separated model
    # catalog co-hosted on this chip ("" = no zoo, byte-identical single-model
    # serving); TPU_ZOO_HOT caps how many stay HBM-resident at once; cold
    # models park as host-RAM param trees and TPU_ZOO_SWAP=0 turns demand
    # swap-in into a hard 503 instead (residency becomes static).
    tpu_zoo_models: str = field(default_factory=lambda: getenv("TPU_ZOO_MODELS", ""))
    tpu_zoo_hot: int = field(default_factory=lambda: getenv_int("TPU_ZOO_HOT", 1))
    tpu_zoo_swap: bool = field(default_factory=lambda: getenv("TPU_ZOO_SWAP", "1") != "0")
    # per-tenant goodput quotas (executor/scheduler.py token buckets):
    # "alice=600,bob=300,*=1000" in tok/s; "" = unmetered (no tenant gate).
    # TPU_TENANT_HEADER renames the request header the tenant id is read
    # from (default X-Tenant-Id, api/inference.py).
    tpu_tenant_quotas: str = field(default_factory=lambda: getenv("TPU_TENANT_QUOTAS", ""))
    tpu_tenant_header: str = field(default_factory=lambda: getenv("TPU_TENANT_HEADER", ""))

    def __post_init__(self) -> None:
        # DB_DSN was documented but never read by any backend (the store is
        # sqlite at DB_PATH, full stop). A silently inert knob is an operator
        # trap — fail loud instead of letting a configured DSN be ignored.
        if os.environ.get("DB_DSN", ""):
            raise RuntimeError(
                "DB_DSN is set but unsupported: the only storage backend is "
                "sqlite at DB_PATH. Unset DB_DSN (or set DB_PATH) to proceed."
            )

    def has_openai(self) -> bool:
        return bool(self.openai_api_key)

    def has_openrouter(self) -> bool:
        return bool(self.openrouter_api_key)

    def warn_embed_dir_gap(self, log) -> None:
        """Deployments that set only TPU_WEIGHTS_DIR: the generator's dir
        deliberately does NOT leak into the embedder (its config.json would
        be authoritative for the wrong model), but the resulting silent
        byte-tokenizer fallback changes embedding outputs — say it out loud
        at every serving entrypoint."""
        if not self.tpu_embed_weights_dir and self.tpu_weights_dir:
            log.warning(
                "TPU_EMBED_WEIGHTS_DIR is unset while TPU_WEIGHTS_DIR=%s: "
                "embedder %s has no checkpoint dir and will use the byte "
                "tokenizer; set TPU_EMBED_WEIGHTS_DIR to its weights dir",
                self.tpu_weights_dir, self.tpu_embed_model,
            )


# enable_compile_cache outcomes, counted not raised: a bad cache dir must
# never take a serving boot down (the engine runs fine, just cold), but the
# failure has to be visible somewhere — warmup_stats()/bench read these.
compile_cache_failures = 0
compile_cache_dir: str | None = None


def compile_cache_path() -> str:
    """Resolve the ONE compile-cache knob. `TPU_COMPILE_CACHE` wins: a path
    enables the cache there; `0`/`off`/`false` force-disables (even when
    JAX_COMPILATION_CACHE_DIR is set — conftest vs production isolation);
    unset falls through to the legacy `JAX_COMPILATION_CACHE_DIR`. Empty
    return = disabled."""
    knob = getenv("TPU_COMPILE_CACHE", "").strip()
    if knob.lower() in ("0", "off", "false", "no"):
        return ""
    if knob:
        return knob
    return getenv("JAX_COMPILATION_CACHE_DIR", "")


def enable_compile_cache(
    path: str | None = None, min_compile_s: float = 1.0
) -> str | None:
    """Persistent XLA compile cache (serving entrypoints, bench, AND
    tests/conftest.py — the one knobbed path): first 8B compiles cost 1-2
    min each on a remote chip, and engine restarts would otherwise re-pay
    the whole executable zoo (prompt buckets, compact buckets, admit
    shapes). The warmup planner's background AOT compiles land here too,
    which is what makes them stick for the next boot (warmup_pack.py).

    STRICTLY OPT-IN via TPU_COMPILE_CACHE (fallback:
    JAX_COMPILATION_CACHE_DIR): measured on the CPU backend, cached AOT
    executables can carry target-machine features the loader host lacks
    (+prefer-no-scatter et al.) — XLA loads them anyway with SIGILL
    warnings and a large slowdown. Only enable where you've verified the
    backend round-trips its own cache.

    Failures COUNT (module counter `compile_cache_failures`), never raise:
    an unwritable cache dir degrades to a cold boot, not a dead one.
    Returns the active cache dir, or None when disabled/failed."""
    import logging as _logging

    global compile_cache_failures, compile_cache_dir
    cache_dir = path if path is not None else compile_cache_path()
    if not cache_dir:
        return None
    # jax imports only on the enabled path — proxy-only workers deliberately
    # never import jax (worker/__main__.py lazy-imports inside its engines
    # branch), and this must stay a no-op for them
    import jax
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_compile_s)
        )
    except Exception:  # noqa: BLE001 — counted, not raised (see docstring)
        compile_cache_failures += 1
        _logging.getLogger("config").warning(
            "compile cache at %s unavailable (failure #%d)",
            cache_dir, compile_cache_failures, exc_info=True,
        )
        return None
    compile_cache_dir = cache_dir
    return cache_dir
