"""Token estimation and prompt/message helpers.

Parity targets in the reference:
  - EstimateTokens: len/4 chars, floor 256 (`core/internal/routing/router.go:113-123`)
  - MessagesToPrompt (`router.go`, tested at `router_test.go:68-97`)
  - `<think>` tag splitting in worker results (`worker/llm_worker/main.py:207-219`)
"""

from __future__ import annotations

from typing import Any

MIN_ESTIMATED_TOKENS = 256


def estimate_tokens(text: str) -> int:
    """Cheap context-size estimate: one token per 4 chars, floor 256.

    Mirrors reference `router.go:113-123`; used for context-bucket routing
    before any tokenizer runs.
    """
    if not text:
        return MIN_ESTIMATED_TOKENS
    return max(MIN_ESTIMATED_TOKENS, len(text) // 4)


def messages_to_prompt(messages: list[dict[str, Any]]) -> str:
    """Flatten chat messages to a single prompt string ("role: content" lines)."""
    parts: list[str] = []
    for m in messages or []:
        role = str(m.get("role", "user"))
        content = m.get("content", "")
        if isinstance(content, list):  # OpenAI content-parts form
            content = " ".join(
                str(p.get("text", "")) for p in content if isinstance(p, dict)
            )
        parts.append(f"{role}: {content}")
    return "\n".join(parts)


def split_think(text: str) -> tuple[str, str]:
    """Split `<think>...</think>` reasoning from the visible answer.

    Returns (thinking, answer). Mirrors reference worker behavior
    (`worker/llm_worker/main.py:207-219`): if the text starts with a think
    block, extract it; otherwise thinking is empty.
    """
    if not text:
        return "", text
    stripped = text.lstrip()
    if not stripped.startswith("<think>"):
        return "", text
    end = stripped.find("</think>")
    if end < 0:
        # Unterminated think block: everything is thinking.
        return stripped[len("<think>"):].strip(), ""
    thinking = stripped[len("<think>"):end].strip()
    # reference strips the remaining response fully
    # (worker/llm_worker/main.py:218: `response.strip()`)
    answer = stripped[end + len("</think>"):].strip()
    return thinking, answer
