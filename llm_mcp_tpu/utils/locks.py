"""Lock-ordering discipline for the engine/watchdog/HTTP thread state.

The serving process runs four kinds of threads against shared state: the
engine loop, its watchdog, HTTP handler threads (stats surfaces, admission
checks), and — under a mesh — the slice leader's command channel. The soak
tests guard against deadlock empirically; this module audits the ordering
rule itself (the ROADMAP A2 gap): every lock carries a global *rank*, and a
thread may only acquire a lock of strictly higher rank than any lock it
already holds. Rank assignments live in doc/concurrency.md; violations
raise immediately instead of deadlocking some unlucky soak run later.

OrderedLock is a drop-in for threading.Lock (acquire/release/context
manager/locked), so call sites and tests that poke `pool._lock` directly
keep working.
"""

from __future__ import annotations

import threading

_tls = threading.local()


def _held() -> list[tuple[int, str]]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def held_ranks() -> list[tuple[int, str]]:
    """(rank, name) of locks the calling thread currently holds, in
    acquisition order — for assertions in tests and debug dumps."""
    return list(_held())


class LockOrderError(RuntimeError):
    """A thread tried to acquire a lock out of rank order (potential
    deadlock with any thread taking the same locks in the opposite
    order)."""


class OrderedLock:
    """threading.Lock plus a process-wide rank discipline.

    Acquiring a lock whose rank is <= the highest rank the thread already
    holds raises LockOrderError (this also rejects re-entrant acquisition,
    which would deadlock a plain Lock anyway). The check is per-thread
    bookkeeping only — no extra synchronization on the hot path.
    """

    def __init__(self, name: str, rank: int):
        self.name = name
        self.rank = rank
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        stack = _held()
        if stack and stack[-1][0] >= self.rank:
            raise LockOrderError(
                f"lock order violation: acquiring {self.name!r} (rank "
                f"{self.rank}) while holding {stack[-1][1]!r} (rank "
                f"{stack[-1][0]}); see doc/concurrency.md"
            )
        ok = (
            self._lock.acquire(blocking, timeout)
            if timeout != -1
            else self._lock.acquire(blocking)
        )
        if ok:
            stack.append((self.rank, self.name))
        return ok

    def release(self) -> None:
        stack = _held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == (self.rank, self.name):
                del stack[i]
                break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"OrderedLock({self.name!r}, rank={self.rank})"
