from .config import Config, getenv, getenv_int, getenv_float, getenv_bool
from .tokens import estimate_tokens, messages_to_prompt, split_think

__all__ = [
    "Config",
    "getenv",
    "getenv_int",
    "getenv_float",
    "getenv_bool",
    "estimate_tokens",
    "messages_to_prompt",
    "split_think",
]
