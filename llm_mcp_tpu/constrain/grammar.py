"""Byte-level grammar automaton: the constraint subsystem's core formalism.

A constraint — JSON schema, regex subset, literal choice list — compiles
down to ONE shared representation: a context-free grammar over BYTES,
walked by a pushdown automaton whose configurations are interned into
integer states. Working at the byte level (not characters, not tokens)
is what makes the token-mask layer (masks.py) tokenizer-agnostic: a
token is legal in a state iff its UTF-8 bytes drive the automaton
through live states, whatever the tokenizer's segmentation.

Representation:

- a grammar is ``rules: {name: (alternative, ...)}`` where an
  alternative is a tuple of symbols and a symbol is either
  ``("t", frozenset_of_byte_values)`` (terminal byte class) or
  ``("r", rule_name)`` (rule reference). Repetition is expressed by
  RIGHT recursion (``R: [] | [x, R]``) — left recursion would loop the
  closure and is rejected.
- an automaton configuration is a STACK of frames ``(rule, alt, dot)``;
  a state is a frozenset of closure-normalized stacks. The empty stack
  in a state means the input so far is a complete sentence (accepting —
  the EOS bit in the token mask). States are interned to dense ints and
  byte transitions are memoized, so agent loops re-walking the same
  schema pay the closure cost once per distinct state.

Pure stdlib by design (see the purity manifest): the automaton advances
on the engine host thread and inside follower processes, and the API
layer compiles specs before any device work exists.
"""

from __future__ import annotations

Sym = tuple  # ("t", frozenset[int]) | ("r", str)
Alt = tuple  # tuple[Sym, ...]

# interning cap: a pathological grammar (huge enum cross-products) must
# fail compilation loudly instead of eating the serve host's RAM
MAX_STATES = 50_000


class GrammarError(ValueError):
    """Unsupported or malformed constraint spec (API surfaces this as 400)."""


def _check_rules(rules: dict) -> None:
    for name, alts in rules.items():
        for alt in alts:
            for sym in alt:
                if sym[0] == "r" and sym[1] not in rules:
                    raise GrammarError(
                        f"rule {name!r} references undefined rule {sym[1]!r}"
                    )


class ByteAutomaton:
    """Pushdown walker over a byte grammar with interned states.

    ``start_state`` is always 0. ``step(sid, byte)`` returns the next
    state id or -1 (dead). ``accepting(sid)`` is True when the bytes so
    far form a complete sentence of the grammar."""

    def __init__(self, rules: dict[str, tuple[Alt, ...]], start: str):
        _check_rules(rules)
        if start not in rules:
            raise GrammarError(f"start rule {start!r} undefined")
        self.rules = rules
        self.start = start
        self._states: list[frozenset] = []
        self._ids: dict[frozenset, int] = {}
        self._step: dict[tuple[int, int], int] = {}
        init: set[tuple] = set()
        for ai in range(len(rules[start])):
            self._close(((start, ai, 0),), init, set())
        self._intern(frozenset(init))  # state 0

    # -- closure ------------------------------------------------------------

    def _close(self, stack: tuple, out: set, seen: set) -> None:
        """Expand one stack until its top symbol is a terminal (emit) or
        the stack empties (emit () — accepting). ``seen`` guards nullable
        cycles; genuinely left-recursive grammars are rejected here."""
        if stack in seen:
            return
        seen.add(stack)
        if not stack:
            out.add(())
            return
        rule, ai, dot = stack[-1]
        alt = self.rules[rule][ai]
        if dot >= len(alt):
            # completed frame: pop, advance the parent past its rule-ref
            parent = stack[:-1]
            if not parent:
                out.add(())
                return
            pr, pa, pd = parent[-1]
            self._close(parent[:-1] + ((pr, pa, pd + 1),), out, seen)
            return
        sym = alt[dot]
        if sym[0] == "t":
            out.add(stack)
            return
        sub = sym[1]
        for ai2 in range(len(self.rules[sub])):
            self._close(stack + ((sub, ai2, 0),), out, seen)

    def _intern(self, state: frozenset) -> int:
        sid = self._ids.get(state)
        if sid is None:
            if len(self._states) >= MAX_STATES:
                raise GrammarError(
                    f"constraint automaton exceeded {MAX_STATES} states"
                )
            sid = len(self._states)
            self._states.append(state)
            self._ids[state] = sid
        return sid

    # -- walking ------------------------------------------------------------

    @property
    def start_state(self) -> int:
        return 0

    def accepting(self, sid: int) -> bool:
        return sid >= 0 and () in self._states[sid]

    def step(self, sid: int, byte: int) -> int:
        """Next state id after consuming ``byte``, or -1 (dead)."""
        if sid < 0:
            return -1
        key = (sid, byte)
        nxt = self._step.get(key)
        if nxt is not None:
            return nxt
        out: set[tuple] = set()
        seen: set[tuple] = set()
        for stack in self._states[sid]:
            if not stack:
                continue  # acceptance is not a continuation
            rule, ai, dot = stack[-1]
            sym = self.rules[rule][ai][dot]
            if byte in sym[1]:
                self._close(
                    stack[:-1] + ((rule, ai, dot + 1),), out, seen
                )
        nxt = self._intern(frozenset(out)) if out else -1
        self._step[key] = nxt
        return nxt

    def step_bytes(self, sid: int, data: bytes) -> int:
        for b in data:
            sid = self.step(sid, b)
            if sid < 0:
                return -1
        return sid

    def live_bytes(self, sid: int) -> frozenset[int]:
        """The union of byte classes the state can consume — the trie
        walk in masks.py prunes children outside this set up front."""
        if sid < 0:
            return frozenset()
        out: set[int] = set()
        for stack in self._states[sid]:
            if not stack:
                continue
            rule, ai, dot = stack[-1]
            out |= self.rules[rule][ai][dot][1]
        return frozenset(out)

    def n_states(self) -> int:
        return len(self._states)


# ---------------------------------------------------------------------------
# grammar construction helpers (shared by schema.py and the regex compiler)
# ---------------------------------------------------------------------------


def t(byte_set) -> Sym:
    return ("t", frozenset(byte_set))


def lit(text: str | bytes) -> Alt:
    """A literal byte sequence as a symbol tuple."""
    data = text.encode("utf-8") if isinstance(text, str) else text
    return tuple(("t", frozenset((b,))) for b in data)


class RuleBuilder:
    """Gensym'd rule accumulation — every compiler in the subsystem
    funnels through one of these so rule names never collide."""

    def __init__(self, prefix: str = "g"):
        self.rules: dict[str, tuple[Alt, ...]] = {}
        self._prefix = prefix
        self._n = 0

    def fresh(self) -> str:
        self._n += 1
        return f"{self._prefix}{self._n}"

    def add(self, name: str, alts: list[Alt]) -> str:
        self.rules[name] = tuple(tuple(a) for a in alts)
        return name

    def rule(self, alts: list[Alt]) -> str:
        return self.add(self.fresh(), alts)

    def star(self, seq: Alt) -> str:
        """R: [] | [seq..., R] — right-recursive Kleene star."""
        name = self.fresh()
        self.rules[name] = ((), tuple(seq) + (("r", name),))
        return name


# ---------------------------------------------------------------------------
# regex subset → grammar
# ---------------------------------------------------------------------------

_CLASS_ESCAPES = {
    "d": frozenset(range(0x30, 0x3A)),
    "w": frozenset(
        list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
        + list(range(0x61, 0x7B)) + [0x5F]
    ),
    "s": frozenset((0x20, 0x09, 0x0A, 0x0D, 0x0C, 0x0B)),
    "n": frozenset((0x0A,)),
    "t": frozenset((0x09,)),
    "r": frozenset((0x0D,)),
}
_ANY = frozenset(b for b in range(256) if b != 0x0A)


class _RegexParser:
    """Recursive-descent compiler for the supported regex subset:
    literals, ``.``, ``[...]`` classes (ranges, negation), ``(...)``
    groups, ``|`` alternation, ``* + ?`` and ``{m}/{m,}/{m,n}``
    quantifiers, and the ``\\d \\w \\s \\n \\t \\r`` escapes. Anchors and
    backreferences are rejected — the automaton always full-matches."""

    def __init__(self, pattern: str, rb: RuleBuilder):
        self.p = pattern
        self.i = 0
        self.rb = rb

    def _err(self, msg: str) -> GrammarError:
        return GrammarError(f"regex: {msg} at offset {self.i} in {self.p!r}")

    def peek(self) -> str:
        return self.p[self.i] if self.i < len(self.p) else ""

    def parse(self) -> str:
        name = self._alternation()
        if self.i != len(self.p):
            raise self._err(f"unexpected {self.peek()!r}")
        return name

    def _alternation(self) -> str:
        branches = [self._concat()]
        while self.peek() == "|":
            self.i += 1
            branches.append(self._concat())
        return self.rb.rule([(("r", b),) for b in branches])

    def _concat(self) -> str:
        syms: list[Sym] = []
        while self.peek() not in ("", "|", ")"):
            syms.extend(self._quantified())
        return self.rb.rule([tuple(syms)])

    def _quantified(self) -> list[Sym]:
        atom = self._atom()
        ch = self.peek()
        if ch == "*":
            self.i += 1
            return [("r", self.rb.star(atom))]
        if ch == "+":
            self.i += 1
            return list(atom) + [("r", self.rb.star(atom))]
        if ch == "?":
            self.i += 1
            return [("r", self.rb.rule([(), tuple(atom)]))]
        if ch == "{":
            end = self.p.find("}", self.i)
            if end == -1:
                raise self._err("unterminated {m,n}")
            body = self.p[self.i + 1 : end]
            self.i = end + 1
            try:
                if "," not in body:
                    lo = hi = int(body)
                elif body.endswith(","):
                    lo, hi = int(body[:-1]), -1
                else:
                    a, b = body.split(",", 1)
                    lo, hi = int(a), int(b)
            except ValueError:
                raise self._err(f"bad repetition {{{body}}}") from None
            if lo < 0 or (hi != -1 and hi < lo) or lo > 256:
                raise self._err(f"bad repetition bounds {{{body}}}")
            syms: list[Sym] = []
            for _ in range(lo):
                syms.extend(atom)
            if hi == -1:
                syms.append(("r", self.rb.star(atom)))
            else:
                opt = self.rb.rule([(), tuple(atom)])
                syms.extend([("r", opt)] * (hi - lo))
            return syms
        return list(atom)

    def _atom(self) -> Alt:
        ch = self.peek()
        if ch == "":
            raise self._err("dangling quantifier or empty atom")
        if ch == "(":
            self.i += 1
            if self.p[self.i : self.i + 2] == "?:":
                self.i += 2
            name = self._alternation()
            if self.peek() != ")":
                raise self._err("unbalanced group")
            self.i += 1
            return (("r", name),)
        if ch == "[":
            return (("t", self._char_class()),)
        if ch == ".":
            self.i += 1
            return (("t", _ANY),)
        if ch in ")|*+?{":
            raise self._err(f"unexpected {ch!r}")
        if ch == "\\":
            self.i += 1
            esc = self.peek()
            if esc == "":
                raise self._err("dangling escape")
            self.i += 1
            cls = _CLASS_ESCAPES.get(esc)
            if cls is not None:
                return (("t", cls),)
            if esc in "^$":
                raise self._err("anchors are not supported (always full-match)")
            return lit(esc)
        self.i += 1
        return lit(ch)

    def _char_class(self) -> frozenset[int]:
        self.i += 1  # consume [
        negate = self.peek() == "^"
        if negate:
            self.i += 1
        out: set[int] = set()
        first = True
        while True:
            ch = self.peek()
            if ch == "":
                raise self._err("unterminated character class")
            if ch == "]" and not first:
                self.i += 1
                break
            first = False
            if ch == "\\":
                self.i += 1
                esc = self.peek()
                self.i += 1
                cls = _CLASS_ESCAPES.get(esc)
                if cls is not None:
                    out |= cls
                    continue
                lo_b = ord(esc)
            else:
                self.i += 1
                lo_b = ord(ch)
            if lo_b > 0xFF:
                raise self._err("non-Latin-1 character in class")
            if self.peek() == "-" and self.p[self.i + 1 : self.i + 2] not in ("]", ""):
                self.i += 1
                hi_c = self.peek()
                self.i += 1
                if hi_c == "\\":
                    hi_c = self.peek()
                    self.i += 1
                hi_b = ord(hi_c)
                if hi_b < lo_b or hi_b > 0xFF:
                    raise self._err("bad class range")
                out |= set(range(lo_b, hi_b + 1))
            else:
                out.add(lo_b)
        if negate:
            out = set(range(256)) - out
        if not out:
            raise self._err("empty character class")
        return frozenset(out)


def regex_to_grammar(pattern: str) -> tuple[dict[str, tuple[Alt, ...]], str]:
    """Compile the supported regex subset to (rules, start)."""
    if not isinstance(pattern, str) or not pattern:
        raise GrammarError("regex constraint needs a non-empty pattern string")
    rb = RuleBuilder("rx")
    start = _RegexParser(pattern, rb).parse()
    return rb.rules, start


def choices_to_grammar(choices) -> tuple[dict[str, tuple[Alt, ...]], str]:
    """Literal-alternatives constraint: exactly one of ``choices``."""
    if (
        not isinstance(choices, (list, tuple))
        or not choices
        or not all(isinstance(c, str) and c for c in choices)
    ):
        raise GrammarError("choice constraint needs a non-empty string list")
    rb = RuleBuilder("ch")
    start = rb.rule([lit(c) for c in choices])
    return rb.rules, start
