"""Constraint specs → byte grammars.

``build_grammar(spec)`` is the single entry point the compiler cache
keys on. A spec is a plain dict (what api/inference.py distills from
``response_format`` / ``tools``) with a ``type`` of:

- ``json_schema``: ``{"type": "json_schema", "schema": {...}}`` — the
  draft-ish subset agents actually send: ``type`` (object/array/string/
  number/integer/boolean/null), ``properties`` (+``required`` — we emit
  every listed property, in schema order, a documented simplification
  that keeps the automaton small and output canonical), ``items``,
  ``enum``/``const``, ``anyOf``/``oneOf``, and ``$ref`` into ``$defs``/
  ``definitions`` (recursive schemas become recursive rules, which the
  pushdown handles natively).
- ``json_object``: any syntactically valid JSON object (the OpenAI
  free-form JSON mode).
- ``regex``: ``{"type": "regex", "pattern": "..."}`` (subset, see
  grammar._RegexParser).
- ``choice``: ``{"type": "choice", "choices": ["a", "b"]}`` — exactly
  one literal.

The emitted JSON is COMPACT (no whitespace between tokens): every byte
the model may produce is one the grammar demands, so the mask never has
to reason about optional separators and the automaton stays minimal.

Unsupported constructs raise ``GrammarError`` → the API returns 400
rather than silently generating unconstrained output.

Pure stdlib (see the purity manifest) — compilation runs on the API and
engine host threads before any device work exists.
"""

from __future__ import annotations

import json

from .grammar import (
    Alt,
    ByteAutomaton,
    GrammarError,
    RuleBuilder,
    choices_to_grammar,
    lit,
    regex_to_grammar,
)

# printable string payload bytes: anything >= 0x20 except '"' and '\'
# (multi-byte UTF-8 continuation bytes land here too — the automaton is
# byte-level, so non-ASCII text inside strings just works)
_STR_PLAIN = frozenset(
    b for b in range(0x20, 0x100) if b not in (0x22, 0x5C)
)
_HEX = frozenset(
    list(range(0x30, 0x3A)) + list(range(0x41, 0x47)) + list(range(0x61, 0x67))
)
_DIGIT = frozenset(range(0x30, 0x3A))
_DIGIT19 = frozenset(range(0x31, 0x3A))


def _json_string_rules(rb: RuleBuilder) -> str:
    """Shared rules for a JSON string literal ("..." with escapes)."""
    if "jstr" in rb.rules:
        return "jstr"
    esc_simple = ("t", frozenset(b'"\\/bfnrt'))
    uesc = (("t", frozenset((0x75,))),) + (("t", _HEX),) * 4  # uXXXX
    char = rb.add(
        "jstr_c",
        [
            (("t", _STR_PLAIN),),
            (("t", frozenset((0x5C,))), esc_simple),
            (("t", frozenset((0x5C,))),) + uesc,
        ],
    )
    chars = rb.add("jstr_cs", [(), (("r", char), ("r", "jstr_cs"))])
    return rb.add(
        "jstr",
        [(("t", frozenset((0x22,))), ("r", chars), ("t", frozenset((0x22,))))],
    )


def _json_number_rules(rb: RuleBuilder, integer: bool = False) -> str:
    name = "jint" if integer else "jnum"
    if name in rb.rules:
        return name
    digits1 = rb.rules.get("jdig1")
    if digits1 is None:
        digit = ("t", _DIGIT)
        rb.add("jdigs", [(), (digit, ("r", "jdigs"))])  # digit*
        rb.add("jdig1", [(digit, ("r", "jdigs"))])  # digit+
    int_part = rb.add(
        f"{name}_i",
        [
            (("t", frozenset((0x30,))),),  # 0
            (("t", _DIGIT19), ("r", "jdigs")),  # [1-9] digit*
        ],
    )
    minus = rb.rule([(), (("t", frozenset((0x2D,))),)])  # -?
    if integer:
        return rb.add(name, [(("r", minus), ("r", int_part))])
    frac = rb.rule(
        [(), (("t", frozenset((0x2E,))), ("r", "jdig1"))]
    )  # (. digit+)?
    sign = rb.rule([(), (("t", frozenset(b"+-")),)])
    exp = rb.rule(
        [(), (("t", frozenset(b"eE")), ("r", sign), ("r", "jdig1"))]
    )  # ([eE][+-]?digit+)?
    return rb.add(
        name,
        [(("r", minus), ("r", int_part), ("r", frac), ("r", exp))],
    )


def _generic_json_rules(rb: RuleBuilder) -> str:
    """Any JSON value — used by json_object mode and additionalProperties-
    free fallbacks. Mutually recursive rules; the pushdown nests freely."""
    if "jval" in rb.rules:
        return "jval"
    jstr = _json_string_rules(rb)
    jnum = _json_number_rules(rb)
    rb.add(
        "jval",
        [
            (("r", jstr),),
            (("r", jnum),),
            lit("true"),
            lit("false"),
            lit("null"),
            (("r", "jobj"),),
            (("r", "jarr"),),
        ],
    )
    member = rb.add(
        "jmem", [(("r", jstr), ("t", frozenset((0x3A,))), ("r", "jval"))]
    )
    mem_tail = rb.add(
        "jmem_t",
        [(), (("t", frozenset((0x2C,))), ("r", member), ("r", "jmem_t"))],
    )
    rb.add(
        "jobj",
        [
            lit("{}"),
            (
                ("t", frozenset((0x7B,))),
                ("r", member),
                ("r", "jmem_t"),
                ("t", frozenset((0x7D,))),
            ),
        ],
    )
    val_tail = rb.add(
        "jval_t",
        [(), (("t", frozenset((0x2C,))), ("r", "jval"), ("r", "jval_t"))],
    )
    rb.add(
        "jarr",
        [
            lit("[]"),
            (
                ("t", frozenset((0x5B,))),
                ("r", "jval"),
                ("r", val_tail),
                ("t", frozenset((0x5D,))),
            ),
        ],
    )
    return "jval"


class _SchemaCompiler:
    MAX_DEPTH = 64

    def __init__(self, root: dict):
        self.rb = RuleBuilder("js")
        self.root = root
        self._refs: dict[str, str] = {}  # $ref path -> rule name

    def compile(self) -> tuple[dict, str]:
        start = self._node(self.root, 0)
        return self.rb.rules, start

    def _resolve_ref(self, ref: str) -> dict:
        if ref == "#":
            return self.root
        if not isinstance(ref, str) or not ref.startswith("#/"):
            raise GrammarError(f"unsupported $ref {ref!r} (only '#/...' paths)")
        node = self.root
        for part in ref[2:].split("/"):
            part = part.replace("~1", "/").replace("~0", "~")
            if not isinstance(node, dict) or part not in node:
                raise GrammarError(f"$ref {ref!r} does not resolve")
            node = node[part]
        if not isinstance(node, dict):
            raise GrammarError(f"$ref {ref!r} target is not a schema object")
        return node

    def _node(self, sch, depth: int) -> str:
        if depth > self.MAX_DEPTH:
            raise GrammarError("schema nesting exceeds supported depth")
        if sch is True or sch == {}:
            return _generic_json_rules(self.rb)
        if not isinstance(sch, dict):
            raise GrammarError("schema node must be an object")
        if "$ref" in sch:
            ref = sch["$ref"]
            name = self._refs.get(ref)
            if name is None:
                # pre-register before building so recursion terminates
                name = self.rb.fresh()
                self._refs[ref] = name
                target = self._resolve_ref(ref)
                inner = self._node(target, depth + 1)
                self.rb.add(name, [(("r", inner),)])
            return name
        if "const" in sch:
            return self.rb.rule([lit(json.dumps(sch["const"], separators=(",", ":")))])
        if "enum" in sch:
            vals = sch["enum"]
            if not isinstance(vals, list) or not vals:
                raise GrammarError("enum must be a non-empty list")
            return self.rb.rule(
                [lit(json.dumps(v, separators=(",", ":"))) for v in vals]
            )
        for key in ("anyOf", "oneOf"):
            if key in sch:
                subs = sch[key]
                if not isinstance(subs, list) or not subs:
                    raise GrammarError(f"{key} must be a non-empty list")
                names = [self._node(s, depth + 1) for s in subs]
                return self.rb.rule([(("r", n),) for n in names])
        typ = sch.get("type")
        if isinstance(typ, list):
            names = [self._node({**sch, "type": t_}, depth + 1) for t_ in typ]
            return self.rb.rule([(("r", n),) for n in names])
        if typ == "object" or (typ is None and "properties" in sch):
            return self._object(sch, depth)
        if typ == "array":
            return self._array(sch, depth)
        if typ == "string":
            return _json_string_rules(self.rb)
        if typ == "number":
            return _json_number_rules(self.rb)
        if typ == "integer":
            return _json_number_rules(self.rb, integer=True)
        if typ == "boolean":
            return self.rb.rule([lit("true"), lit("false")])
        if typ == "null":
            return self.rb.rule([lit("null")])
        if typ is None:
            return _generic_json_rules(self.rb)
        raise GrammarError(f"unsupported schema type {typ!r}")

    def _object(self, sch: dict, depth: int) -> str:
        props = sch.get("properties")
        if props is None:
            return self._generic_object()
        if not isinstance(props, dict):
            raise GrammarError("properties must be an object")
        if not props:
            return self.rb.rule([lit("{}")])
        # every listed property is emitted, in schema order — documented
        # simplification: canonical output, O(props) automaton size
        seq: list = [("t", frozenset((0x7B,)))]
        for i, (key, sub) in enumerate(props.items()):
            if i:
                seq.append(("t", frozenset((0x2C,))))
            seq.extend(lit(json.dumps(key, separators=(",", ":")) + ":"))
            seq.append(("r", self._node(sub, depth + 1)))
        seq.append(("t", frozenset((0x7D,))))
        return self.rb.rule([tuple(seq)])

    def _generic_object(self) -> str:
        _generic_json_rules(self.rb)
        return "jobj"

    def _array(self, sch: dict, depth: int) -> str:
        items = sch.get("items")
        inner = (
            self._node(items, depth + 1)
            if items is not None
            else _generic_json_rules(self.rb)
        )
        tail = self.rb.fresh()
        self.rb.rules[tail] = (
            (),
            (("t", frozenset((0x2C,))), ("r", inner), ("r", tail)),
        )
        min_items = sch.get("minItems", 0)
        alts: list[Alt] = []
        if min_items in (0, None):
            alts.append(lit("[]"))
        alts.append(
            (
                ("t", frozenset((0x5B,))),
                ("r", inner),
                ("r", tail),
                ("t", frozenset((0x5D,))),
            )
        )
        return self.rb.rule(alts)


def schema_to_grammar(schema) -> tuple[dict, str]:
    if not isinstance(schema, (dict, bool)):
        raise GrammarError("json_schema constraint needs a schema object")
    return _SchemaCompiler(schema if isinstance(schema, dict) else {}).compile()


def build_grammar(spec: dict) -> tuple[dict, str]:
    """Spec dict → (rules, start). Raises GrammarError on bad specs."""
    if not isinstance(spec, dict):
        raise GrammarError("constraint spec must be an object")
    typ = spec.get("type")
    if typ == "json_schema":
        return schema_to_grammar(spec.get("schema"))
    if typ == "json_object":
        rb = RuleBuilder("jo")
        _generic_json_rules(rb)
        return rb.rules, "jobj"
    if typ == "regex":
        return regex_to_grammar(spec.get("pattern"))
    if typ == "choice":
        return choices_to_grammar(spec.get("choices"))
    raise GrammarError(f"unsupported constraint type {typ!r}")


def build_automaton(spec: dict) -> ByteAutomaton:
    rules, start = build_grammar(spec)
    return ByteAutomaton(rules, start)
