"""Grammar automata → packed token bitmasks over the model vocabulary.

The byte automaton (grammar.py) knows which BYTES may come next; the
engine needs which TOKENS may come next, as a static-shape
``[ceil(V/32)] uint32`` bitmask the sampler can expand on-device
(ops/sampling.py `expand_mask`). This module owns that lift:

- ``TokenByteTable``: byte trie over the tokenizer's vocabulary. A
  token's byte string is ``tokenizer.decode([tid]).encode()`` — exact
  for the byte tokenizer, and the documented approximation for BPE
  vocabularies (byte-fallback merges decode to the replacement char and
  are conservatively dropped from masks; structure bytes like ``{":,``
  always decode cleanly, which is what schema grammars constrain).
- ``CompiledConstraint``: automaton + trie with two memos — per-state
  packed masks (built by one trie DFS per distinct automaton state) and
  ``(state, token) → state`` transitions. Agent loops re-visiting the
  same schema states pay the DFS once.
- ``SlotAutomaton``: the per-engine-slot cursor — current state, the
  consumed token ids (migration wire replays these on the destination
  host), draft filtering for the speculative composer, and the
  ``logit_bias`` arrays that ride the same mask-add path.
- ``ConstraintCompiler``: LRU over compiled constraints keyed by the
  sha256 of the canonical spec JSON (`TPU_CONSTRAIN_CACHE` entries).

numpy-only on purpose (purity manifest: jax forbidden): everything here
runs on the engine host thread; the device only ever sees the packed
words.
"""

from __future__ import annotations

import hashlib
import json
import time
from collections import OrderedDict

import numpy as np

from .grammar import ByteAutomaton, GrammarError
from .schema import build_grammar

__all__ = [
    "TokenByteTable",
    "CompiledConstraint",
    "SlotAutomaton",
    "ConstraintCompiler",
    "mask_words",
    "spec_key",
]


def mask_words(n_vocab: int) -> int:
    """W — packed words per mask row for a (padded) vocab size."""
    return (int(n_vocab) + 31) // 32


def spec_key(spec: dict) -> str:
    """Cache key: sha256 of the canonical (sorted, compact) spec JSON."""
    blob = json.dumps(spec, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class TokenByteTable:
    """Byte trie over token ids. One per (tokenizer, n_vocab) pair —
    the engine builds it lazily on the first constrained request."""

    def __init__(self, tokenizer, n_vocab: int):
        self.n_vocab = int(n_vocab)
        self.eos_id = int(getattr(tokenizer, "eos_id", -1))
        specials = {
            int(getattr(tokenizer, "pad_id", -1)),
            int(getattr(tokenizer, "bos_id", -1)),
            self.eos_id,
        }
        # trie node = [ids_ending_here, {byte: child}]
        self.root: list = [[], {}]
        n = min(int(getattr(tokenizer, "vocab_size", n_vocab)), self.n_vocab)
        # the byte tokenizer's id→byte map is exact — use it directly so
        # continuation bytes (which decode to U+FFFD alone) stay maskable
        # and multi-byte UTF-8 output remains reachable under constraint
        offset = getattr(tokenizer, "OFFSET", None)
        self.n_tokens = 0
        for tid in range(n):
            if tid in specials:
                continue
            if offset is not None:
                if not (offset <= tid < offset + 256):
                    continue
                data = bytes((tid - offset,))
            else:
                text = tokenizer.decode([tid])
                if not text or "�" in text:
                    continue  # byte-fallback token: conservatively unmaskable
                data = text.encode("utf-8")
            node = self.root
            for b in data:
                node = node[1].setdefault(b, [[], {}])
            node[0].append(tid)
            self.n_tokens += 1


class CompiledConstraint:
    """One compiled (automaton, vocabulary) product with memoized masks
    and transitions. Shared across every slot serving the same spec."""

    def __init__(self, automaton: ByteAutomaton, table: TokenByteTable, stats=None):
        self.automaton = automaton
        self.table = table
        self.W = mask_words(table.n_vocab)
        self._masks: dict[int, np.ndarray] = {}
        self._adv: dict[tuple[int, int], int] = {}
        # shared counters (owned by the ConstraintCompiler)
        self._stats = stats if stats is not None else {}
        # dead-state mask: EOS only, so a desynced slot terminates fast
        self._dead = np.zeros(self.W, dtype=np.uint32)
        if 0 <= table.eos_id < table.n_vocab:
            self._dead[table.eos_id >> 5] |= np.uint32(1 << (table.eos_id & 31))

    def mask(self, sid: int) -> np.ndarray:
        """Packed [W] uint32 row of tokens legal in ``sid`` (read-only)."""
        if sid < 0:
            return self._dead
        row = self._masks.get(sid)
        if row is not None:
            self._stats["mask_hits"] = self._stats.get("mask_hits", 0) + 1
            return row
        t0 = time.perf_counter()
        row = np.zeros(self.W, dtype=np.uint32)
        auto = self.automaton
        # DFS the byte trie, carrying the automaton state alongside
        stack = [(self.table.root, sid)]
        while stack:
            node, st = stack.pop()
            for tid in node[0]:
                row[tid >> 5] |= np.uint32(1 << (tid & 31))
            children = node[1]
            if not children:
                continue
            live = auto.live_bytes(st)
            for b, child in children.items():
                if b in live:
                    nxt = auto.step(st, b)
                    if nxt >= 0:
                        stack.append((child, nxt))
        # the root frame's ending-ids were set unconditionally above;
        # correct: the root has none (no zero-byte tokens)
        if auto.accepting(sid) and 0 <= self.table.eos_id < self.table.n_vocab:
            row[self.table.eos_id >> 5] |= np.uint32(1 << (self.table.eos_id & 31))
        row.setflags(write=False)
        self._masks[sid] = row
        self._stats["mask_builds"] = self._stats.get("mask_builds", 0) + 1
        self._stats["mask_build_s"] = (
            self._stats.get("mask_build_s", 0.0) + (time.perf_counter() - t0)
        )
        return row

    def advance(self, sid: int, tid: int) -> int:
        """State after emitting token ``tid`` from ``sid`` (-1 = dead).
        EOS maps an accepting state to itself (terminal)."""
        if sid < 0:
            return -1
        if tid == self.table.eos_id:
            return sid if self.automaton.accepting(sid) else -1
        key = (sid, tid)
        nxt = self._adv.get(key)
        if nxt is None:
            nxt = self._advance_slow(sid, tid)
            self._adv[key] = nxt
        return nxt

    def _advance_slow(self, sid: int, tid: int) -> int:
        # locate the token's byte path; tokens absent from the trie
        # (specials, byte-fallback) are never legal
        path = self._token_bytes(tid)
        if path is None:
            return -1
        return self.automaton.step_bytes(sid, path)

    def _token_bytes(self, tid: int) -> bytes | None:
        cache = getattr(self, "_tok_bytes", None)
        if cache is None:
            cache = self._tok_bytes = {}
            stack = [(self.table.root, b"")]
            while stack:
                node, prefix = stack.pop()
                for t in node[0]:
                    cache[t] = prefix
                for b, child in node[1].items():
                    stack.append((child, prefix + bytes((b,))))
        return cache.get(tid)

    def allows(self, sid: int, tid: int) -> bool:
        row = self.mask(sid)
        if not (0 <= tid < self.table.n_vocab):
            return False
        return bool((int(row[tid >> 5]) >> (tid & 31)) & 1)

    def n_states(self) -> int:
        return self.automaton.n_states()


class SlotAutomaton:
    """Per-slot constraint cursor. ``cc=None`` means bias-only (a
    pass-through automaton: every token legal, only ``logit_bias``
    rides the mask-add path)."""

    __slots__ = ("cc", "spec", "state", "consumed", "illegal",
                 "bias_ids", "bias_vals", "_ones")

    def __init__(self, cc: CompiledConstraint | None, spec=None,
                 bias_ids=None, bias_vals=None, n_vocab: int = 0):
        self.cc = cc
        self.spec = spec  # the raw spec dict — migration re-compiles from it
        self.state = cc.automaton.start_state if cc is not None else 0
        self.consumed: list[int] = []
        self.illegal = 0
        self.bias_ids = list(bias_ids or [])
        self.bias_vals = list(bias_vals or [])
        W = mask_words(cc.table.n_vocab if cc is not None else n_vocab)
        ones = np.full(W, 0xFFFFFFFF, dtype=np.uint32)
        ones.setflags(write=False)
        self._ones = ones

    @property
    def constrained(self) -> bool:
        return self.cc is not None

    @property
    def accepting(self) -> bool:
        if self.cc is None:
            return True
        return self.cc.automaton.accepting(self.state)

    def mask_row(self) -> np.ndarray:
        if self.cc is None:
            return self._ones
        return self.cc.mask(self.state)

    def allows(self, tid: int) -> bool:
        if self.cc is None:
            return True
        if tid == self.cc.table.eos_id:
            return self.cc.automaton.accepting(self.state)
        return self.cc.allows(self.state, tid)

    def advance(self, tid: int) -> bool:
        """Consume an EMITTED token. Returns False (and counts it) if
        the token was automaton-illegal — which the mask makes
        impossible by construction; the counter is the proof."""
        tid = int(tid)
        self.consumed.append(tid)
        if self.cc is None:
            return True
        nxt = self.cc.advance(self.state, tid)
        if nxt < 0:
            self.illegal += 1
            self.state = -1
            return False
        if tid != self.cc.table.eos_id:
            self.state = nxt
        return True

    def replay(self, tids) -> None:
        """Migration restore: re-walk already-emitted ids on a fresh
        cursor so the destination host resumes mid-constraint."""
        for tid in tids:
            self.advance(tid)

    def filter_draft(self, draft: list[int]) -> list[int]:
        """Longest automaton-legal prefix of a speculative draft — the
        composition guarantee that drafts are constraint-legal by
        construction."""
        if self.cc is None:
            return draft
        sid = self.state
        out: list[int] = []
        for tid in draft:
            tid = int(tid)
            if tid == self.cc.table.eos_id:
                break  # the drafter never needs to propose EOS
            nxt = self.cc.advance(sid, tid)
            if nxt < 0:
                break
            out.append(tid)
            sid = nxt
        return out

    def masks_for_draft(self, draft: list[int]) -> np.ndarray:
        """[len(draft)+1, W] packed rows: row j constrains the token at
        draft position j (row 0 = current state). spec_verify applies
        these BEFORE accept/reject, keeping rejection resampling exact
        under the constraint."""
        n = len(draft) + 1
        if self.cc is None:
            return np.broadcast_to(self._ones, (n, self._ones.shape[0])).copy()
        rows = np.empty((n, self.cc.W), dtype=np.uint32)
        sid = self.state
        rows[0] = self.cc.mask(sid)
        for j, tid in enumerate(draft):
            sid = self.cc.advance(sid, int(tid))
            rows[j + 1] = self.cc.mask(sid)
            if sid < 0:
                break  # remaining rows stay EOS-only via mask(-1) next iter
        return rows


class ConstraintCompiler:
    """LRU compile cache keyed by schema hash + the slot-automaton
    factory. One per engine; stats surface at /v1/debug/constrain."""

    def __init__(self, tokenizer, n_vocab: int, cache_size: int = 64):
        self._tokenizer = tokenizer
        self.n_vocab = int(n_vocab)
        self.cache_size = max(1, int(cache_size))
        self._table: TokenByteTable | None = None
        self._cache: OrderedDict[str, CompiledConstraint] = OrderedDict()
        self.stats_d: dict = {
            "hits": 0, "misses": 0, "evictions": 0, "compile_s": 0.0,
            "mask_builds": 0, "mask_hits": 0, "mask_build_s": 0.0,
        }

    def table(self) -> TokenByteTable:
        if self._table is None:
            self._table = TokenByteTable(self._tokenizer, self.n_vocab)
        return self._table

    def compile(self, spec: dict) -> CompiledConstraint:
        key = spec_key(spec)
        cc = self._cache.get(key)
        if cc is not None:
            self._cache.move_to_end(key)
            self.stats_d["hits"] += 1
            return cc
        self.stats_d["misses"] += 1
        t0 = time.perf_counter()
        rules, start = build_grammar(spec)
        automaton = ByteAutomaton(rules, start)
        cc = CompiledConstraint(automaton, self.table(), stats=self.stats_d)
        self.stats_d["compile_s"] += time.perf_counter() - t0
        self._cache[key] = cc
        while len(self._cache) > self.cache_size:
            self._cache.popitem(last=False)
            self.stats_d["evictions"] += 1
        return cc

    def make(self, spec: dict | None, logit_bias=None) -> SlotAutomaton:
        """Slot automaton for a request: compiled constraint (cached),
        pass-through when only ``logit_bias`` is present."""
        bias_ids, bias_vals = [], []
        for pair in logit_bias or []:
            bias_ids.append(int(pair[0]))
            bias_vals.append(float(pair[1]))
        cc = self.compile(spec) if spec else None
        return SlotAutomaton(
            cc, spec=spec, bias_ids=bias_ids, bias_vals=bias_vals,
            n_vocab=self.n_vocab,
        )

    def stats(self) -> dict:
        d = dict(self.stats_d)
        d["entries"] = len(self._cache)
        d["cache_size"] = self.cache_size
        d["vocab_tokens"] = self._table.n_tokens if self._table else 0
        return d
