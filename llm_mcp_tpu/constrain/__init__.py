"""Grammar-constrained decoding subsystem.

Compiles JSON-schema / regex / choice constraints into byte-level
pushdown automata (grammar.py, schema.py), lifts them to packed
per-state token bitmasks over the model vocabulary (masks.py), and
hands the engine a per-slot cursor (`SlotAutomaton`) whose masks ride
the static-shape mask-then-sample path in ops/sampling.py — including
through speculative verify, where per-position masks are applied
before accept/reject so rejection resampling stays distribution-exact
under the constraint.

Env knobs (registered in doc/README.md):

- ``TPU_CONSTRAIN`` (default 1): kill switch. 0 disables the whole
  subsystem — requests carrying constraints run unconstrained and no
  constrained executables are ever traced.
- ``TPU_CONSTRAIN_CACHE`` (default 64): LRU entries in the per-engine
  schema compile cache.
- ``LLM_MCP_TPU_CN_BIAS_MAX`` (default 64): max ``logit_bias`` entries
  per request (the static width of the bias scatter in the sampler).
"""

from __future__ import annotations

import os

from .grammar import ByteAutomaton, GrammarError
from .masks import (
    CompiledConstraint,
    ConstraintCompiler,
    SlotAutomaton,
    TokenByteTable,
    mask_words,
    spec_key,
)
from .schema import build_automaton, build_grammar

__all__ = [
    "ByteAutomaton",
    "CompiledConstraint",
    "ConstraintCompiler",
    "GrammarError",
    "SlotAutomaton",
    "TokenByteTable",
    "build_automaton",
    "build_grammar",
    "constrain_enabled",
    "mask_words",
    "spec_key",
]


def constrain_enabled() -> bool:
    """The `TPU_CONSTRAIN` kill switch, read at engine construction."""
    return os.environ.get("TPU_CONSTRAIN", "1") != "0"
