"""`python -m llm_mcp_tpu.worker` — boot a pull worker.

Env-configured like the reference worker container (compose.yml llmworker
service): CORE_URL points at the core; TPU engines load in-process when
WORKER_LOAD_ENGINES=1 (the TPU-VM deployment shape), otherwise jobs proxy
to routed device addrs.
"""

from __future__ import annotations

import logging
import os
import signal


def main() -> None:
    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO"),
        format='{"ts":"%(asctime)s","level":"%(levelname)s","logger":"%(name)s","msg":"%(message)s"}',
    )
    from ..api.providers import CloudClient
    from ..utils.config import Config, enable_compile_cache
    from .client import CoreClient
    from .executors import Executors
    from .worker import Worker

    cfg = Config()
    enable_compile_cache()
    core_url = os.environ.get("CORE_URL", "http://localhost:8080")

    gen_engines: dict = {}
    embed_engines: dict = {}
    if os.environ.get("WORKER_LOAD_ENGINES", "") in ("1", "true"):
        import jax.numpy as jnp

        from ..executor import EmbeddingEngine, GenerationEngine
        from ..parallel import distributed

        mesh = None
        if cfg.tpu_mesh_shape:
            distributed.initialize()
            mesh = distributed.make_global_mesh(cfg.tpu_mesh_shape)

        model = cfg.tpu_model
        gen_engines[model] = GenerationEngine(
            model,
            mesh=mesh,
            max_slots=cfg.tpu_max_slots,
            max_seq_len=cfg.tpu_max_seq_len,
            dtype=jnp.bfloat16,
            weights_dir=cfg.tpu_weights_dir,
            quant=cfg.tpu_quant,
            kv_quant=cfg.tpu_kv_quant,
            prefill_chunk=cfg.tpu_prefill_chunk,
            decode_compact=cfg.tpu_decode_compact,
            prompt_cache_mb=cfg.tpu_prompt_cache_mb,
            prefill_buckets=cfg.tpu_prefill_buckets,
            target_ttft_ms=cfg.tpu_target_ttft_ms,
        ).start()
        cfg.warn_embed_dir_gap(logging.getLogger("worker"))
        embed_engines[cfg.tpu_embed_model] = EmbeddingEngine(
            cfg.tpu_embed_model,
            max_seq_len=min(cfg.tpu_max_seq_len, 8192),
            dtype=jnp.bfloat16,
            weights_dir=cfg.tpu_embed_weights_dir,
            quant=cfg.tpu_embed_quant,
        )

    cloud = CloudClient(cfg) if (cfg.has_openrouter() or cfg.has_openai()) else None
    # gRPC transport when configured (reference worker parity: gRPC-only,
    # `main.py:536-599`); HTTP otherwise. Worker is transport-agnostic.
    grpc_target = os.environ.get("CORE_GRPC_TARGET", "")
    client = CoreClient(core_url)
    if grpc_target:
        try:
            from ..rpc.client import GrpcCoreClient

            client = GrpcCoreClient(grpc_target)
        except Exception as e:
            # Downgrading to HTTP is only safe when CORE_URL was explicitly
            # configured — otherwise fail fast instead of silently spinning
            # against the localhost default.
            if not os.environ.get("CORE_URL"):
                raise SystemExit(
                    f"CORE_GRPC_TARGET={grpc_target!r} set but gRPC client "
                    f"unavailable ({e}) and no CORE_URL fallback configured"
                ) from e
            logging.getLogger("main").warning(
                "gRPC unavailable (%s); falling back to HTTP at %s", e, core_url
            )
    worker = Worker(
        client,
        Executors(gen_engines=gen_engines, embed_engines=embed_engines, cloud=cloud),
        worker_id=cfg.worker_id,
        name=cfg.worker_name,
        kinds=[k.strip() for k in cfg.worker_kinds.split(",") if k.strip()],
        lease_seconds=float(cfg.worker_lease_seconds),
    )
    signal.signal(signal.SIGTERM, lambda *_: worker.stop())
    signal.signal(signal.SIGINT, lambda *_: worker.stop())
    worker.run()
    for e in gen_engines.values():
        e.shutdown()


if __name__ == "__main__":
    main()
