"""Pull-based execution workers.

Parity: reference `worker/llm_worker/main.py` (603 LoC) — stateless workers
that register with the core, claim jobs from the durable queue, heartbeat
their leases, execute by kind, and report results. The TPU twist: a worker
can EMBED the JAX engines in-process (the common case on a TPU VM — no HTTP
hop for the hot path) or proxy to a routed executor node's OpenAI-compatible
surface, the way the reference worker proxied to Ollama.
"""

from .client import CoreClient
from .executors import Executors
from .worker import Worker

__all__ = ["CoreClient", "Executors", "Worker"]
