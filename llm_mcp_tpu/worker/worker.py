"""The worker pull loop: register → claim → heartbeat → execute → report.

Parity: reference `worker/llm_worker/main.py:536-599` — register with
retry-forever (545-552), 1.5 s idle claim poll (563-566), per-job heartbeat
daemon thread at lease/2 (521-533, 573-579), complete/fail reporting with
requeue semantics, connection-failure → device offline side-channel
(592-595). Workers are stateless; scale-out is just more processes
(SURVEY.md §2.2 data-parallel scale-out).
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import uuid
from typing import Any

from ..telemetry import tracing
from ..utils.faults import FaultInjected, maybe_fail
from .client import CoreClient, TerminalHTTPError
from .executors import ExecutionError, Executors

log = logging.getLogger("worker")

IDLE_POLL_S = 1.5  # main.py:563-566
REGISTER_RETRY_S = 3.0


class Worker:
    def __init__(
        self,
        client: CoreClient,
        executors: Executors,
        *,
        worker_id: str = "",
        name: str = "",
        kinds: list[str] | None = None,
        lease_seconds: float = 30.0,
        idle_poll_s: float = IDLE_POLL_S,
    ):
        self.client = client
        self.executors = executors
        self.worker_id = worker_id or f"worker-{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        self.name = name or self.worker_id
        # WORKER_KINDS specialization (main.py:539-540): empty = all kinds
        self.kinds = kinds or []
        self.lease_seconds = lease_seconds
        self.idle_poll_s = idle_poll_s
        self.jobs_done = 0
        self.jobs_failed = 0
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()

    def register_forever(self) -> None:
        """Retry registration until the core answers (main.py:545-552)."""
        while not self._stop.is_set():
            try:
                self.client.register(self.worker_id, self.name, self.kinds)
                log.info("registered as %s kinds=%s", self.worker_id, self.kinds or "all")
                return
            except (ConnectionError, TerminalHTTPError) as e:
                log.warning("register failed (%s), retrying", e)
                self._stop.wait(REGISTER_RETRY_S)

    def run(self) -> None:
        self.register_forever()
        while not self._stop.is_set():
            if not self.run_once():
                self._stop.wait(self.idle_poll_s)

    # -- one claim cycle (test seam) ---------------------------------------

    def run_once(self) -> bool:
        """Claim and execute at most one job. Returns True if one ran."""
        try:
            job = self.client.claim(self.worker_id, self.kinds, self.lease_seconds)
        except (ConnectionError, TerminalHTTPError) as e:
            log.warning("claim failed: %s", e)
            return False
        if not job:
            return False
        self._execute(job)
        return True

    # -- execution ---------------------------------------------------------

    def _execute(self, job: dict[str, Any]) -> None:
        job_id = str(job["id"])
        kind = str(job.get("kind") or "")
        payload = job.get("payload") or {}
        log.info("job %s kind=%s model=%s", job_id, kind, payload.get("model", ""))
        # join the submitting request's trace (payload-propagated context);
        # jobs submitted without one get their own root trace. The span
        # wraps dispatch AND the completion report, so the client's
        # complete/fail POSTs carry the trace header too.
        ctx = str(payload.get("_traceparent") or "")
        with tracing.get_tracer().span(
            "worker.execute",
            parent=ctx or tracing.NEW_TRACE,
            attrs={"job_id": job_id, "kind": kind, "worker_id": self.worker_id},
        ):
            self._execute_traced(job_id, kind, payload)

    def _execute_traced(self, job_id: str, kind: str, payload: dict[str, Any]) -> None:
        hb_stop = threading.Event()
        hb = threading.Thread(
            target=self._heartbeat_loop, args=(job_id, hb_stop),
            name=f"hb-{job_id[:8]}", daemon=True,
        )
        hb.start()
        t0 = time.monotonic()
        try:
            result = self.executors.dispatch(kind, payload)
        except ExecutionError as e:
            hb_stop.set()
            hb.join(timeout=2.0)
            self._report_failure(job_id, payload, str(e), e.connection_failure)
            return
        except Exception as e:  # defensive: never leave a job leased
            hb_stop.set()
            hb.join(timeout=2.0)
            self._report_failure(job_id, payload, f"{type(e).__name__}: {e}", False)
            return
        hb_stop.set()
        hb.join(timeout=2.0)

        try:
            # chaos site: the job's work is DONE but the completion report
            # never happens — exactly what a worker crash between execute
            # and complete looks like; lease expiry must requeue the job.
            maybe_fail("worker.complete", job_id)
        except FaultInjected:
            log.warning("fault: dropping completion report for %s (simulated death)", job_id)
            return

        metrics = {
            "worker_id": self.worker_id,
            "duration_ms": round((time.monotonic() - t0) * 1000.0, 1),
        }
        try:
            self.client.complete(job_id, self.worker_id, result, metrics)
            self.jobs_done += 1
        except (ConnectionError, TerminalHTTPError) as e:
            # Lease expiry will requeue the job; the attempt's work is lost
            # but the queue stays consistent (crash-recovery semantics).
            log.error("complete %s failed: %s", job_id, e)

    def _report_failure(
        self, job_id: str, payload: dict[str, Any], error: str, connection_failure: bool
    ) -> None:
        self.jobs_failed += 1
        log.warning("job %s failed: %s", job_id, error)
        try:
            self.client.fail(job_id, self.worker_id, error)
        except (ConnectionError, TerminalHTTPError) as e:
            log.error("fail report for %s failed: %s", job_id, e)
        if connection_failure and payload.get("device_id"):
            # Device-unreachable class errors additionally push the device
            # offline so routing stops selecting it (main.py:189-196,592-595).
            self.client.report_offline(str(payload["device_id"]), error)

    def _heartbeat_loop(self, job_id: str, stop: threading.Event) -> None:
        """Extend the lease every lease/2 seconds while the job runs
        (main.py:521-533); a dead worker simply stops heartbeating and the
        lease expires."""
        interval = max(1.0, self.lease_seconds / 2.0)
        while not stop.wait(interval):
            try:
                if not self.client.heartbeat(job_id, self.worker_id, self.lease_seconds):
                    log.warning("heartbeat rejected for %s (lease lost)", job_id)
                    return
            except (ConnectionError, TerminalHTTPError) as e:
                log.warning("heartbeat failed for %s: %s", job_id, e)
