"""Job-kind executors: the worker's dispatch table.

Parity: reference `worker/llm_worker/main.py:330-449` kind dispatch with
executors for local inference (`_ollama_generate` 222-243, `_ollama_embed`
246-261), cloud chat (`openai.chat` 274-299, `openrouter.chat` 302-327),
benchmark kinds (471-518), and the `echo` pipeline probe (449). Cross-cutting
behaviors kept: `<think>` splitting (207-219), cost calc from routed pricing
(199-204), per-stage ms timing in results (240-243).

Local execution is either in-process (engines loaded in this worker) or a
proxy to the routed device's OpenAI-compatible surface via `device_addr` —
the analog of `ollama_addr` resolution (main.py:163-177).
"""

from __future__ import annotations

import logging
import socket
import time
import urllib.error
from typing import Any

from ..utils.faults import maybe_fail
from ..utils.tokens import estimate_tokens, messages_to_prompt, split_think
from .client import post_json

log = logging.getLogger("worker.executors")

PROXY_TIMEOUT_S = 120.0  # reference chat/embed proxy timeout (handlers.go:1816,2082)
BENCH_PROMPT = "Write a short story about a lighthouse keeper who discovers a hidden door."


class ExecutionError(RuntimeError):
    """Job failed; `connection_failure` marks device-unreachable errors that
    should additionally report the device offline (main.py:189-196)."""

    def __init__(self, msg: str, connection_failure: bool = False):
        super().__init__(msg)
        self.connection_failure = connection_failure


def _payload_cost(payload: dict[str, Any], tokens_in: int, tokens_out: int) -> float | None:
    """USD cost from routing-injected pricing (`_price_in_1m`/`_price_out_1m`,
    router.go:513-516; cost calc main.py:199-204)."""
    pin = payload.get("_price_in_1m")
    pout = payload.get("_price_out_1m")
    if pin is None and pout is None:
        return None
    return (tokens_in * float(pin or 0.0) + tokens_out * float(pout or 0.0)) / 1e6


class Executors:
    def __init__(
        self,
        *,
        gen_engines: dict[str, Any] | None = None,
        embed_engines: dict[str, Any] | None = None,
        cloud: Any = None,  # providers.CloudClient | None
        http_post_json=None,  # injectable for tests
    ):
        self.gen_engines = gen_engines or {}
        self.embed_engines = embed_engines or {}
        self.cloud = cloud
        self._post = http_post_json or self._default_post

    # -- dispatch ----------------------------------------------------------

    def dispatch(self, kind: str, payload: dict[str, Any]) -> dict[str, Any]:
        maybe_fail("worker.execute", f"kind={kind}")
        provider = str(payload.get("provider") or "tpu")
        if kind == "echo":
            # optional bounded delay: lets scale-out tests make work
            # non-instant so claims spread across workers deterministically.
            # Client-controlled, so hard-capped small and parse-safe.
            try:
                delay = float(payload.get("delay_s") or 0.0)
            except (TypeError, ValueError):
                delay = 0.0
            if delay > 0:
                time.sleep(min(delay, 2.0))
            return {"echo": payload.get("data", payload), "ok": True}
        if kind.startswith("benchmark."):
            return self._benchmark(kind.removeprefix("benchmark."), payload)
        if kind in ("generate", "chat"):
            if provider in ("openai", "openrouter"):
                return self._cloud_chat(payload)
            return self._generate(payload)
        if kind == "embed":
            if provider in ("openai", "openrouter"):
                return self._cloud_embed(payload)
            return self._embed(payload)
        raise ExecutionError(f"unknown job kind: {kind}")

    # -- local generation --------------------------------------------------

    def _gen_params(self, payload: dict[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if payload.get("max_tokens") is not None:
            out["max_tokens"] = int(payload["max_tokens"])
        if payload.get("temperature") is not None:
            out["temperature"] = float(payload["temperature"])
        if payload.get("top_k") is not None:
            out["top_k"] = int(payload["top_k"])
        if payload.get("top_p") is not None:
            out["top_p"] = float(payload["top_p"])
        if payload.get("stop"):
            out["stop"] = list(payload["stop"])
        return out

    def _prompt_of(self, payload: dict[str, Any]) -> str:
        prompt = str(payload.get("prompt") or "")
        if not prompt and payload.get("messages"):
            prompt = messages_to_prompt(payload["messages"])
        return prompt

    def _generate(self, payload: dict[str, Any]) -> dict[str, Any]:
        model = str(payload.get("model") or "")
        prompt = self._prompt_of(payload)
        t0 = time.monotonic()
        engine = self.gen_engines.get(model)
        if engine is not None:
            out = engine.generate(prompt, **self._gen_params(payload))
            usage = out.get("usage", {})
            text = out["text"]
            tokens_in = int(usage.get("prompt_tokens") or 0)
            tokens_out = int(usage.get("completion_tokens") or 0)
        else:
            text, tokens_in, tokens_out = self._remote_generate(payload, prompt)
        ms = (time.monotonic() - t0) * 1000.0
        thinking, answer = split_think(text)
        result: dict[str, Any] = {
            "response": answer,
            "model": model,
            "tokens_in": tokens_in,
            "tokens_out": tokens_out,
            "ms": round(ms, 1),
        }
        if thinking:
            result["thinking"] = thinking
        cost = _payload_cost(payload, tokens_in, tokens_out)
        if cost is not None:
            result["cost_usd"] = round(cost, 8)
        return result

    def _remote_generate(
        self, payload: dict[str, Any], prompt: str
    ) -> tuple[str, int, int]:
        """Proxy to the routed device's /v1/chat/completions (non-stream) —
        the worker-side analog of POST {ollama_addr}/api/generate."""
        addr = str(payload.get("device_addr") or "")
        if not addr:
            raise ExecutionError(
                f"model {payload.get('model')!r} not loaded locally and no device_addr routed"
            )
        body = {
            "model": payload.get("model"),
            "messages": [{"role": "user", "content": prompt}],
            "stream": False,
            **{
                k: payload[k]
                for k in ("max_tokens", "temperature", "top_p", "stop")
                if payload.get(k) is not None
            },
        }
        doc = self._post_device(addr, "/v1/chat/completions", body)
        try:
            text = doc["choices"][0]["message"]["content"]
        except (KeyError, IndexError, TypeError):
            raise ExecutionError(f"malformed completion from {addr}: {doc}") from None
        usage = doc.get("usage") or {}
        return (
            str(text),
            int(usage.get("prompt_tokens") or estimate_tokens(prompt)),
            int(usage.get("completion_tokens") or estimate_tokens(str(text))),
        )

    # -- local embeddings --------------------------------------------------

    def _embed(self, payload: dict[str, Any]) -> dict[str, Any]:
        model = str(payload.get("model") or "")
        texts = payload.get("input") or payload.get("texts") or []
        if isinstance(texts, str):
            texts = [texts]
        dims = payload.get("dimensions")
        t0 = time.monotonic()
        engine = self.embed_engines.get(model)
        if engine is not None:
            vectors, total_tokens = engine.embed(
                [str(t) for t in texts], dimensions=int(dims) if dims else None
            )
        else:
            vectors, total_tokens = self._remote_embed(payload, texts)
        ms = (time.monotonic() - t0) * 1000.0
        result = {
            "embeddings": vectors,
            "model": model,
            "count": len(vectors),
            "tokens_in": total_tokens,
            "ms": round(ms, 1),
        }
        cost = _payload_cost(payload, total_tokens, 0)
        if cost is not None:
            result["cost_usd"] = round(cost, 8)
        return result

    def _remote_embed(
        self, payload: dict[str, Any], texts: list[Any]
    ) -> tuple[list[list[float]], int]:
        addr = str(payload.get("device_addr") or "")
        if not addr:
            raise ExecutionError(
                f"model {payload.get('model')!r} not loaded locally and no device_addr routed"
            )
        body: dict[str, Any] = {"model": payload.get("model"), "input": texts}
        if payload.get("dimensions"):
            body["dimensions"] = payload["dimensions"]
        doc = self._post_device(addr, "/v1/embeddings", body)
        vectors = [d.get("embedding", []) for d in doc.get("data", [])]
        total = int((doc.get("usage") or {}).get("prompt_tokens") or 0)
        return vectors, total

    # -- cloud -------------------------------------------------------------

    def _cloud_chat(self, payload: dict[str, Any]) -> dict[str, Any]:
        if self.cloud is None:
            raise ExecutionError("cloud provider not configured")
        messages = payload.get("messages") or [
            {"role": "user", "content": self._prompt_of(payload)}
        ]
        t0 = time.monotonic()
        doc = self.cloud.chat(
            {
                "model": payload.get("model"),
                "messages": messages,
                **{
                    k: payload[k]
                    for k in ("max_tokens", "temperature", "top_p")
                    if payload.get(k) is not None
                },
            }
        )
        ms = (time.monotonic() - t0) * 1000.0
        text = ""
        try:
            text = doc["choices"][0]["message"]["content"] or ""
        except (KeyError, IndexError, TypeError):
            pass
        usage = doc.get("usage") or {}
        tokens_in = int(usage.get("prompt_tokens") or 0)
        tokens_out = int(usage.get("completion_tokens") or 0)
        thinking, answer = split_think(text)
        result = {
            "response": answer,
            "model": doc.get("model") or payload.get("model"),
            "tokens_in": tokens_in,
            "tokens_out": tokens_out,
            "ms": round(ms, 1),
        }
        if thinking:
            result["thinking"] = thinking
        cost = _payload_cost(payload, tokens_in, tokens_out)
        if cost is not None:
            result["cost_usd"] = round(cost, 8)
        return result

    def _cloud_embed(self, payload: dict[str, Any]) -> dict[str, Any]:
        if self.cloud is None:
            raise ExecutionError("cloud provider not configured")
        texts = payload.get("input") or payload.get("texts") or []
        if isinstance(texts, str):
            texts = [texts]
        dims = payload.get("dimensions")
        t0 = time.monotonic()
        doc = self.cloud.embed(
            str(payload.get("model") or ""), [str(t) for t in texts],
            int(dims) if dims else None,
        )
        ms = (time.monotonic() - t0) * 1000.0
        vectors = [d.get("embedding", []) for d in doc.get("data", [])]
        total = int((doc.get("usage") or {}).get("prompt_tokens") or 0)
        result = {
            "embeddings": vectors,
            "model": payload.get("model"),
            "count": len(vectors),
            "tokens_in": total,
            "ms": round(ms, 1),
        }
        cost = _payload_cost(payload, total, 0)
        if cost is not None:
            result["cost_usd"] = round(cost, 8)
        return result

    # -- benchmarks --------------------------------------------------------

    def _benchmark(self, task: str, payload: dict[str, Any]) -> dict[str, Any]:
        """Measured tps/latency for (device, model, task) — feeds the
        `benchmarks` table that device ranking consults. Reference computed
        tps from Ollama's eval_duration (main.py:471-518); here timing comes
        from our own engine/proxy wall clock."""
        payload = dict(payload)
        payload.setdefault("prompt", BENCH_PROMPT)
        payload.setdefault("max_tokens", int(payload.get("bench_tokens") or 64))
        t0 = time.monotonic()
        if task == "embed":
            payload.setdefault("input", [BENCH_PROMPT] * int(payload.get("bench_batch") or 8))
            r = self._embed(payload)
            latency_ms = (time.monotonic() - t0) * 1000.0
            tokens = int(r.get("tokens_in") or 0)
            tps = tokens / (latency_ms / 1000.0) if latency_ms > 0 else 0.0
            return {
                "task_type": "embed",
                "model": r.get("model"),
                "tokens_in": tokens,
                "tokens_out": 0,
                "latency_ms": round(latency_ms, 1),
                "tps": round(tps, 2),
            }
        r = self._generate(payload)
        latency_ms = (time.monotonic() - t0) * 1000.0
        tokens_out = int(r.get("tokens_out") or 0)
        tps = tokens_out / (latency_ms / 1000.0) if latency_ms > 0 else 0.0
        return {
            "task_type": "generate",
            "model": r.get("model"),
            "tokens_in": int(r.get("tokens_in") or 0),
            "tokens_out": tokens_out,
            "latency_ms": round(latency_ms, 1),
            "tps": round(tps, 2),
        }

    # -- device HTTP -------------------------------------------------------

    def _default_post(self, url: str, body: dict[str, Any]) -> tuple[int, dict[str, Any]]:
        # post_json RETURNS HTTP error statuses instead of raising, so the
        # status>=400 branch below stays a policy error (no offline report)
        # and only transport failures count as connection failures.
        return post_json(url, body, PROXY_TIMEOUT_S)

    def _post_device(self, addr: str, path: str, body: dict[str, Any]) -> dict[str, Any]:
        if ":" in addr and not addr.startswith(("http://", "https://")):
            host, _, port = addr.rpartition(":")
            if ":" in host and not host.startswith("["):  # IPv6 (main.py:141-160)
                host = f"[{host}]"
            addr = f"http://{host}:{port}"
        elif not addr.startswith(("http://", "https://")):
            addr = f"http://{addr}"
        try:
            status, doc = self._post(f"{addr}{path}", body)
        except (urllib.error.URLError, socket.timeout, OSError, ValueError) as e:
            raise ExecutionError(f"device {addr} unreachable: {e}", connection_failure=True) from e
        if status >= 400:
            raise ExecutionError(f"device {addr} returned {status}: {doc}")
        return doc
