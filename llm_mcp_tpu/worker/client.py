"""Worker→core protocol client (HTTP).

Parity: the reference worker speaks gRPC to the core
(`worker/llm_worker/main.py:536-599`) with one HTTP side-channel
(`POST /v1/devices/offline`, main.py:180-186). Here the primary transport is
the core's HTTP worker protocol (same routes the gRPC server mirrors); the
gRPC transport is available via `llm_mcp_tpu.rpc`.

Retry policy mirrors main.py:112-138: exponential backoff on connection
errors and 5xx; 4xx are terminal except 429.
"""

from __future__ import annotations

import json
import logging
import socket
import time
import urllib.error
import urllib.request
from typing import Any, Callable

from ..telemetry import tracing

log = logging.getLogger("worker.client")

# post(path, body, timeout) -> (status_code, parsed_json_or_{})
HttpPost = Callable[[str, dict[str, Any] | None, float], tuple[int, dict[str, Any]]]


class TerminalHTTPError(RuntimeError):
    def __init__(self, status: int, body: Any):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body


def post_json(
    url: str,
    body: dict[str, Any] | None,
    timeout: float,
    headers: dict[str, str] | None = None,
) -> tuple[int, dict[str, Any]]:
    """One JSON POST → (status, parsed body). HTTP error statuses are
    RETURNED, not raised — only transport failures raise, so callers can
    distinguish device-unreachable from device-said-no."""
    data = json.dumps(body or {}).encode()
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(url, data=data, method="POST", headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:  # noqa: S310
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except (ValueError, OSError):
            payload = {}
        return e.code, payload


class CoreClient:
    def __init__(
        self,
        base_url: str,
        *,
        http_post: HttpPost | None = None,
        timeout_s: float = 30.0,
        max_retries: int = 5,
        backoff_s: float = 0.5,
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self._post = http_post or self._default_post

    def _default_post(
        self, path: str, body: dict[str, Any] | None, timeout: float
    ) -> tuple[int, dict[str, Any]]:
        # propagate the calling thread's trace context (the worker wraps job
        # execution in a span, so complete/fail reports join the job's trace)
        ctx = tracing.current_traceparent()
        headers = {"traceparent": ctx} if ctx else None
        return post_json(f"{self.base_url}{path}", body, timeout, headers=headers)

    def post(self, path: str, body: dict[str, Any] | None = None) -> dict[str, Any]:
        """POST with backoff. Raises TerminalHTTPError on non-retryable 4xx,
        ConnectionError after retries are exhausted."""
        delay = self.backoff_s
        last_err: Exception | None = None
        for attempt in range(self.max_retries):
            is_last = attempt == self.max_retries - 1
            try:
                status, payload = self._post(path, body, self.timeout_s)
            except (urllib.error.URLError, socket.timeout, OSError, ValueError) as e:
                last_err = e
                log.warning("post %s failed (%s), retry %d", path, e, attempt + 1)
                if not is_last:
                    time.sleep(delay)
                    delay = min(delay * 2, 10.0)
                continue
            if status < 400:
                return payload
            if 400 <= status < 500 and status != 429:
                raise TerminalHTTPError(status, payload)
            last_err = TerminalHTTPError(status, payload)
            if not is_last:
                time.sleep(delay)
                delay = min(delay * 2, 10.0)
        raise ConnectionError(f"post {path}: retries exhausted: {last_err}")

    # -- worker protocol (mirrors grpcserver RPCs / HTTP routes) -----------

    def register(self, worker_id: str, name: str = "", kinds: list[str] | None = None) -> None:
        self.post(
            "/v1/workers/register",
            {"worker_id": worker_id, "name": name, "kinds": kinds or []},
        )

    def claim(
        self, worker_id: str, kinds: list[str] | None = None, lease_seconds: float = 30.0
    ) -> dict[str, Any] | None:
        out = self.post(
            "/v1/jobs/claim",
            {"worker_id": worker_id, "kinds": kinds or [], "lease_seconds": lease_seconds},
        )
        return out.get("job")

    def heartbeat(self, job_id: str, worker_id: str, lease_seconds: float = 30.0) -> bool:
        """False = lease lost (the core answered 409: job no longer running
        under this worker); transport failures still raise."""
        try:
            out = self.post(
                f"/v1/jobs/{job_id}/heartbeat",
                {"worker_id": worker_id, "lease_seconds": lease_seconds},
            )
        except TerminalHTTPError as e:
            if e.status == 409:
                return False
            raise
        return out.get("status") == "ok"

    def complete(
        self,
        job_id: str,
        worker_id: str,
        result: dict[str, Any],
        metrics: dict[str, Any] | None = None,
    ) -> None:
        self.post(
            f"/v1/jobs/{job_id}/complete",
            {"worker_id": worker_id, "result": result, "metrics": metrics or {}},
        )

    def fail(self, job_id: str, worker_id: str, error: str) -> str:
        out = self.post(
            f"/v1/jobs/{job_id}/fail", {"worker_id": worker_id, "error": error}
        )
        return str(out.get("status") or "")

    def report_offline(self, device_id: str, reason: str = "") -> None:
        """Connection-failure side channel (`main.py:180-196`)."""
        try:
            self.post("/v1/devices/offline", {"device_id": device_id, "reason": reason})
        except (ConnectionError, TerminalHTTPError):
            log.warning("offline report for %s failed", device_id)
