"""llm_mcp_tpu — a TPU-native distributed LLM inference router & execution plane.

A brand-new framework with the capabilities of plagness/LLM-MCP (see SURVEY.md):
an OpenAI-compatible API (`/v1/chat/completions` SSE, `/v1/embeddings`), a
durable job queue with lease/heartbeat worker protocol, smart quality-tier
routing with circuit breakers and device limits, cluster discovery, cost
accounting, benchmarks and observability — with the crucial difference that
inference runs **in-process on TPU** via a JAX/XLA executor (pjit-sharded
autoregressive decode, Pallas attention, HBM-resident embedding encoders)
instead of being delegated to external Ollama/cloud endpoints.

Layering (SURVEY.md §7):
  L1 executor   — llm_mcp_tpu.{models,ops,parallel,executor}
  L2 state      — llm_mcp_tpu.state (durable queue + catalog)
  L3 policy     — llm_mcp_tpu.{routing,discovery}
  L4 core API   — llm_mcp_tpu.api
  L5 bridges    — llm_mcp_tpu.mcpsrv
  L6 ops        — ops_deploy/, telemetry
"""

__version__ = "0.1.0"
