from .http import HTTPApi, Request, Response
from .server import CoreServer

__all__ = ["HTTPApi", "Request", "Response", "CoreServer"]
