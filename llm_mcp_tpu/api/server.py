"""Core API server: the single service wiring every surface together.

Parity: the reference's llmcore process (`core/cmd/core/main.go:26-123` boot,
`core/internal/api/server.go:32-62` route table — 27 HTTP routes). Layering
is the same (API → routing policy → state), but L1 execution is in-process:
the server can host TPU generation/embedding engines directly and registers
itself as a device in the catalog, so the routing brain sees it exactly like
any remote executor.

Route inventory (reference server.go:32-62 ↔ here):
  health, metrics, jobs CRUD + claim/complete/fail/heartbeat + SSE stream,
  llm/request, chat/completions, embeddings, models (+sync, +stats),
  devices (+offline), discovery/run, dashboard, costs (summary, balance),
  feedback, benchmarks, workers/register, debug (health, actions, capacity,
  test), knowledge/ingest.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any

from ..executor import EmbeddingEngine, GenerationEngine
from ..routing import CircuitBreaker, LimitsEngine, Router
from ..state.catalog import Catalog, sync_cloud_catalog
from ..state.db import Database
from ..state.queue import JobQueue
from ..telemetry import Metrics, tracing
from ..telemetry import recorder as flight
from ..telemetry import workload
from ..utils.config import Config
from .dashboard import DashboardAPI
from .http import HTTPApi, Request, Response
from .inference import InferenceAPI
from .jobs import JobsAPI
from .providers import CloudClient

log = logging.getLogger("server")

# span name → llmtpu_stage_duration_seconds stage label. rpc.* spans (any
# transport method) all observe under "rpc".
_SPAN_STAGES = {
    "queue.wait": "queue_wait",
    "route": "route",
    "engine.prefill": "prefill",
    "engine.decode": "decode",
    "engine.preempt": "preempt",
    "engine.restore": "restore",
    "engine.migrate_out": "migrate_out",
    "engine.migrate_in": "migrate_in",
}


class CoreServer:
    def __init__(
        self,
        cfg: Config | None = None,
        *,
        db: Database | None = None,
        gen_engines: dict[str, GenerationEngine] | None = None,
        embed_engines: dict[str, EmbeddingEngine] | None = None,
        device_id: str = "tpu-local",
        advertise_addr: str = "",
        zoo: Any = None,  # executor.zoo.ModelZoo | None (TPU_ZOO_MODELS boot)
    ):
        self.cfg = cfg or Config()
        self.db = db or Database(self.cfg.db_path)
        self.queue = JobQueue(self.db)
        self.catalog = Catalog(self.db)
        self.metrics = Metrics()
        # starved_rounds is cumulative per engine; the Prometheus counter
        # advances by the delta observed between engines_info() refreshes
        self._sched_starved: dict[str, float] = {}
        # same delta bookkeeping for the speculation token counters
        self._spec_counts: dict[str, dict[str, float]] = {}
        # and for the KV-pool preempt/restore/shed counters
        self._pool_counts: dict[str, dict[str, float]] = {}
        # and the paged-KV copy-on-write counter (cumulative per engine)
        self._paging_counts: dict[str, float] = {}
        # and the KV migration out/in/bytes counters (cumulative per engine)
        self._migration_counts: dict[str, dict[str, float]] = {}
        self._migration_requeues = 0.0
        # flight recorder / anomaly / watchdog bridges: events_total is
        # process-wide (one ring), anomaly dumps and watchdog transitions
        # are cumulative per engine+detector/state
        self._flight_events = 0.0
        self._anomaly_counts: dict[str, dict[str, float]] = {}
        self._watchdog_counts: dict[str, dict[str, float]] = {}
        # perf observatory: sampled phase walls are cumulative per
        # engine+phase+bucket, bridged by delta like the rest
        self._perf_phase_s: dict[str, dict[str, float]] = {}
        # per-tenant shed counts (perf tenant ledgers) bridge by delta to
        # llmtpu_tenant_shed_total{engine,tenant}; goodput gauges set direct
        self._tenant_shed: dict[str, dict[str, float]] = {}
        # latency waterfall (telemetry/workload.py): cumulative per-stage
        # seconds per engine, bridged by delta to
        # llmtpu_latency_stage_seconds{engine,stage}
        self._latency_stage_s: dict[str, dict[str, float]] = {}
        # fleet prefix tier (routing/prefix.py): engine export/import
        # counters bridge by delta; route outcomes accumulate here for the
        # dashboard/debug surfaces. prefix_sources lets in-process peers
        # (bench, tests) register a duck-typed `prefix_fetch(ids)` source
        # directly; remote peers resolve lazily from their advertised
        # transfer_addr tag through a cached gRPC transfer client.
        self._prefix_tier_counts: dict[str, dict[str, float]] = {}
        self.prefix_sources: dict[str, Any] = {}
        self._prefix_clients: dict[str, Any] = {}
        self.transfer_addr = os.environ.get("TPU_TRANSFER_ADDR", "").strip()
        self._route_prefix = {
            "local": 0.0, "fetch": 0.0, "miss": 0.0,
            "fetch_fail": 0.0, "matched_tokens": 0.0, "fetch_ms": 0.0,
        }
        self._route_prefix_lock = threading.Lock()
        self.limits = LimitsEngine(self.db, strict=self.cfg.strict_model_limits)
        self.circuit = CircuitBreaker()
        self.router = Router(
            self.db,
            circuit=self.circuit,
            limits=self.limits,
            has_openrouter=self.cfg.has_openrouter(),
            has_openai=self.cfg.has_openai(),
        )
        self.cloud = (
            CloudClient(self.cfg)
            if (self.cfg.has_openrouter() or self.cfg.has_openai())
            else None
        )
        self.device_id = device_id
        self.advertise_addr = advertise_addr
        self.gen_engines = gen_engines or {}
        self.embed_engines = embed_engines or {}
        # Model zoo (executor/zoo.py): the router resolves quality tiers
        # resident-first through it, and the inference path swaps parked
        # models in on demand. None ⇒ single-model serving, no zoo code on
        # any path.
        self.zoo = zoo
        self.router.zoo = zoo

        self.inference = InferenceAPI(
            catalog=self.catalog,
            queue=self.queue,
            router=self.router,
            metrics=self.metrics,
            device_id=device_id,
            gen_engines=self.gen_engines,
            embed_engines=self.embed_engines,
            cloud=self.cloud,
            prefix_fetch=self.maybe_prefix_fetch,
            zoo=zoo,
        )
        self.jobs = JobsAPI(
            queue=self.queue,
            catalog=self.catalog,
            router=self.router,
            metrics=self.metrics,
            cfg=self.cfg,
            overload_check=self._jobs_overload_check,
        )
        self.dashboard = DashboardAPI(
            db=self.db,
            queue=self.queue,
            catalog=self.catalog,
            router=self.router,
            cfg=self.cfg,
            engines_info=self.engines_info,
            route_stats=self.route_prefix_stats,
            zoo_stats=lambda: (self.zoo.stats() if self.zoo is not None else None),
        )

        # Process-default tracer: the HTTP layer, router, engines, and
        # workers all land spans in this ring; /v1/traces serves it and the
        # observer below derives the per-stage latency histograms from it.
        self.tracer = tracing.get_tracer()
        self.tracer.add_observer(self._observe_span)

        self.api = HTTPApi()
        self._register_routes()
        self._bg_stop = threading.Event()
        self._bg_threads: list[threading.Thread] = []
        self._identity_cache: dict[str, Any] | None = None
        from ..discovery import Runner as DiscoveryRunner

        self.discovery = DiscoveryRunner(
            self.catalog,
            self.queue,
            limits=self.limits,
            cfg=self.cfg,
            register_local=self.register_local_device,
            self_device_id=device_id,
        )
        from ..planner import Planner

        self.planner = Planner(
            self.cfg,
            self.queue,
            self.catalog,
            cloud=self.cloud,
            gen_models=list(self.gen_engines),
            embed_models=list(self.embed_engines),
            device_id=device_id,
            gen_engines=self.gen_engines,
        )

        # KV migration (executor/migration.py). The coordinator only exists
        # when TPU_MIGRATE is on — with it off the engines never allocate
        # migration queues and none of the paths below run (true no-op).
        self.role = os.environ.get("TPU_ROLE", "both").strip().lower() or "both"
        self.migration = None
        if self.gen_engines and any(
            getattr(e, "_migrate_outbox", None) is not None
            for e in self.gen_engines.values()
        ):
            from ..executor.migration import MigrationCoordinator

            self.migration = MigrationCoordinator(
                self.gen_engines,
                role=self.role,
                drain_low=float(os.environ.get("TPU_MIGRATE_DRAIN_LOW", "0.25")),
                drain_high=float(os.environ.get("TPU_MIGRATE_DRAIN_HIGH", "0.5")),
                burst=int(os.environ.get("TPU_MIGRATE_BURST", "2")),
            )
            # TPU_MIGRATE_PEER=host:port[,host:port...] — remote decode-role
            # engines reachable over the KV transfer RPC (disaggregation
            # across processes). Lazy import: grpc stays optional.
            peers = os.environ.get("TPU_MIGRATE_PEER", "").strip()
            if peers:
                from ..rpc.client import RemoteMigrationTarget

                for addr in (p.strip() for p in peers.split(",")):
                    if addr:
                        self.migration.add_remote(addr, RemoteMigrationTarget(addr))

    # -- KV-pool admission bridge ------------------------------------------

    def _jobs_overload_check(self) -> tuple[bool, float]:
        """Worker claims defer while any local generation engine's KV pool
        is above the admission watermark — same signal as the 429 path on
        /v1/chat/completions, applied to the pull side of the queue. With
        no pool (TPU_KV_HOST_OFFLOAD=0), every engine reports (False, 0)
        and claims proceed untouched."""
        for e in self.gen_engines.values():
            shed, retry = getattr(e, "admission_state", lambda: (False, 0.0))()
            if shed:
                e.note_shed()
                if self.migration is not None:
                    # a shed is exactly the imbalance migration exists to
                    # fix — kick the drain tick instead of waiting it out
                    self.migration.note_pressure()
                return True, retry
        return False, 0.0

    def _kv_headroom_tag(self) -> float | None:
        """Min shed-free headroom across local pooled engines, or None when
        no engine runs a pool (tag omitted → router treats it as 1.0)."""
        vals = []
        for e in self.gen_engines.values():
            ms = getattr(e, "memory_stats", None)
            if ms is None:
                continue
            st = ms()
            if st.get("enabled"):
                vals.append(float(st.get("headroom", 1.0)))
        return min(vals) if vals else None

    def _prefill_cost_tag(self) -> float | None:
        """Measured prefill cost in µs/token across local engines — the
        perf observatory's prefill-family phase walls (admit / chunk /
        pf_rag) divided by the tokens they prefilled. None until enough
        sampled traffic exists; the router then uses its conservative
        default. This is the price side of the prefix-locality score:
        matched tokens × this cost = expected TTFT savings of a hit."""
        wall = tok = 0.0
        for e in self.gen_engines.values():
            pf = getattr(e, "perf_stats", None)
            if pf is None:
                continue
            phases = pf().get("phases", {})
            for p in ("admit", "chunk", "pf_rag"):
                r = phases.get(p) or {}
                wall += float(r.get("host_s", 0.0)) + float(r.get("device_s", 0.0))
                tok += float(r.get("tokens", 0.0))
        if tok <= 0 or wall <= 0:
            return None
        return wall / tok * 1e6

    def _prefix_digest_tag(self) -> dict | None:
        """Union digest of every local engine's resident prefix chains
        (routing/prefix.py merge_digests), or None when no engine caches
        prefixes — tag omitted, peers never score against this device."""
        from ..routing.prefix import merge_digests

        digests = []
        for e in self.gen_engines.values():
            pd = getattr(e, "prefix_digest", None)
            if pd is None:
                continue
            d = pd()
            if d:
                digests.append(d)
        return merge_digests(digests)

    # -- fleet prefix tier (routing/prefix.py; doc/performance.md) ---------

    def maybe_prefix_fetch(self, model: str, engine: Any, prompt: str) -> tuple[str, int]:
        """Serve-path hook (api/inference.py, before dispatch): does this
        engine — or a peer, via the PrefixFetch RPC — already hold the
        prompt's KV prefix? Returns (outcome, matched_tokens); outcome is
        "" when the tier is off or the engine has no prefix cache, else
        local | fetch | miss. A peer is only dialed when its advertised
        digest claims strictly more than the local cache AND at least
        TPU_PREFIX_FETCH_MIN_TOKENS — below that, recompute beats the wire
        (measured crossover; doc/performance.md). Fetch failures degrade
        to the local outcome: the prompt prefills from scratch exactly as
        it would have without the tier."""
        from ..routing import prefix as prefix_fp

        if not prefix_fp.prefix_route_enabled():
            return "", 0
        match_len = getattr(engine, "prefix_match_len", None)
        if match_len is None:
            return "", 0
        try:
            ids = [int(t) for t in engine.tokenizer.encode(prompt)]
        except Exception:
            return "", 0
        local = int(match_len(ids))
        outcome, matched = ("local", local) if local > 0 else ("miss", 0)
        best = self.router.best_prefix_peer(
            model,
            ids,
            exclude_device=self.device_id,
            min_tokens=max(prefix_fp.fetch_min_tokens(), local + 1),
        )
        if best is not None:
            dev, _claimed = best
            src = self._prefix_source_for(dev)
            if src is not None:
                t0 = time.time()
                payload = None
                try:
                    payload = src.prefix_fetch(ids)
                except ConnectionError as e:
                    log.warning("prefix fetch from %s failed: %s", dev.get("id"), e)
                    with self._route_prefix_lock:
                        self._route_prefix["fetch_fail"] += 1
                if payload and engine.prefix_import(payload):
                    matched = int(match_len(ids))
                    outcome = "fetch"
                    with self._route_prefix_lock:
                        self._route_prefix["fetch_ms"] += (time.time() - t0) * 1e3
        self.metrics.route_prefix_hit.labels(outcome=outcome).inc()
        self.metrics.route_prefix_matched_tokens.observe(matched)
        with self._route_prefix_lock:
            self._route_prefix[outcome] += 1
            self._route_prefix["matched_tokens"] += matched
        return outcome, matched

    def _prefix_source_for(self, dev: dict[str, Any]) -> Any:
        """Resolve a peer device row (router.best_prefix_peer, tags parsed)
        to something with `prefix_fetch(ids) -> bytes | None`."""
        src = self.prefix_sources.get(str(dev.get("id") or ""))
        if src is not None:
            return src
        addr = str((dev.get("tags") or {}).get("transfer_addr") or "").strip()
        if not addr:
            return None
        cli = self._prefix_clients.get(addr)
        if cli is None:
            try:
                from ..rpc.client import GrpcTransferClient

                cli = GrpcTransferClient(addr, timeout_s=30.0)
            except Exception:  # grpc not installed on this host
                return None
            self._prefix_clients[addr] = cli
        return cli

    def prefix_export(self, ids: list[int]) -> bytes | None:
        """PrefixFetch service callback (rpc/server.py KVTransferService):
        first local engine holding a resident chain for these prompt ids
        wins — single-model deployments have exactly one candidate."""
        for e in self.gen_engines.values():
            fn = getattr(e, "prefix_export", None)
            if fn is None:
                continue
            payload = fn(ids)
            if payload is not None:
                return payload
        return None

    def prefix_export_hash(self, hash16: str) -> bytes | None:
        """Hash-keyed PrefixFetch callback (boot-time peer warm-fill): the
        requester knows only the fleet digest's head hashes, not the token
        ids behind them — first local engine holding a resident chain whose
        digest head hash matches wins."""
        for e in self.gen_engines.values():
            fn = getattr(e, "prefix_export_by_hash", None)
            if fn is None:
                continue
            payload = fn(hash16)
            if payload is not None:
                return payload
        return None

    def route_prefix_stats(self) -> dict[str, float]:
        with self._route_prefix_lock:
            return dict(self._route_prefix)

    # -- cold start (executor/warmup.py; doc/performance.md) ---------------

    def boot_warmup(self) -> None:
        """Kick every local gen engine's warmup planner: the critical
        prefix (one admit bucket + one prefill executable + one decode
        shape) compiles synchronously here — start() calls this before
        device registration, so the first request never pays a cold XLA
        compile and the first advertisement already carries the warming
        tag — and the rest of the shape zoo fills in on the planner's
        background thread while serving."""
        priors = self._warmup_pack_priors()
        for e in self.gen_engines.values():
            fn = getattr(e, "start_warmup", None)
            if fn is None:
                continue
            try:
                fn(priors=priors)
            except Exception:
                log.exception("warmup planner failed to start")

    @staticmethod
    def _warmup_pack_priors() -> list[dict] | None:
        """Measured compile costs shipped with the compile cache: a warmup
        pack import (scripts/warmup_pack.py) drops warmup_plan.json next to
        the cache entries, and boot auto-loads it so the plan order
        reflects the exporting fleet's cost × hit aggregates even on a
        process with an empty local ledger."""
        from ..utils import config as ucfg

        cache_dir = ucfg.compile_cache_dir or ucfg.compile_cache_path()
        if not cache_dir:
            return None
        try:
            with open(os.path.join(cache_dir, "warmup_plan.json")) as f:
                rows = json.load(f)
        except (OSError, ValueError):
            return None
        return rows if isinstance(rows, list) else None

    def boot_prefix_warm(self, peers: int | None = None) -> int:
        """Peer warm-fill: pull the fleet's hottest resident prefix chains
        at boot. Head hashes are ranked by popularity across online peer
        devices' prefix_digest tags (peers holding the chain, then chain
        length), the top TPU_BOOT_PREFILL_PEERS of them are pulled through
        the hash-keyed PrefixFetch RPC, and the payloads import into the
        local engines — a joining engine serves its first shared-prefix
        request from fetched blocks instead of recomputing them. 0 (the
        default) disables. Returns the number of chains imported."""
        if peers is None:
            try:
                peers = int(os.environ.get("TPU_BOOT_PREFILL_PEERS", "0") or 0)
            except ValueError:
                peers = 0
        if peers <= 0 or not self.gen_engines:
            return 0
        heads: dict[str, dict[str, Any]] = {}
        for dev in self.catalog.list_devices(online_only=True):
            if str(dev.get("id")) == self.device_id:
                continue
            dig = (dev.get("tags") or {}).get("prefix_digest") or {}
            for h, toks in (dig.get("heads") or {}).items():
                ent = heads.setdefault(str(h), {"count": 0, "tokens": 0, "devs": []})
                ent["count"] += 1
                try:
                    ent["tokens"] = max(ent["tokens"], int(toks or 0))
                except (TypeError, ValueError):
                    pass
                ent["devs"].append(dev)
        ranked = sorted(
            heads.items(), key=lambda kv: (-kv[1]["count"], -kv[1]["tokens"], kv[0])
        )
        imported = 0
        for h, ent in ranked[: int(peers)]:
            payload = None
            for dev in ent["devs"]:
                src = self._prefix_source_for(dev)
                fetch = getattr(src, "prefix_fetch_hash", None)
                if fetch is None:
                    continue
                try:
                    payload = fetch(h)
                except ConnectionError as e:
                    log.warning(
                        "boot prefix fetch from %s failed: %s", dev.get("id"), e
                    )
                    payload = None
                if payload:
                    break
            if not payload:
                continue
            for e in self.gen_engines.values():
                imp = getattr(e, "prefix_import", None)
                try:
                    if imp is not None and imp(payload):
                        imported += 1
                        break
                except Exception:
                    log.exception("boot prefix import failed")
        if imported:
            log.info("boot prefix warm-fill: imported %d chain(s)", imported)
        return imported

    # -- local engine device registration ----------------------------------

    def register_local_device(self) -> None:
        """Advertise this process's engines as a schedulable device, with
        loaded models and slot capacity — the analog of discovery upserting
        an Ollama endpoint (`discovery.go:200-280`), self-registered."""
        models = list(self.gen_engines.keys()) + list(self.embed_engines.keys())
        if not models:
            return
        slots = sum(e.max_slots for e in self.gen_engines.values()) or 1
        import jax

        try:
            n_chips = len(jax.devices())
            platform = jax.devices()[0].platform
        except Exception:
            n_chips, platform = 0, "unknown"
        tags = {
            "tpu": platform in ("tpu", "axon"),
            "platform": platform,
            "chips": n_chips,
            "slots": slots,
            "self": True,
        }
        headroom = self._kv_headroom_tag()
        if headroom is not None:
            # router de-ranks saturated devices on this tag (router.py)
            tags["kv_headroom"] = round(headroom, 4)
        if self.role != "both":
            tags["role"] = self.role
        if self.migration is not None:
            # router prefers migration-capable devices among saturated
            # candidates (routing/router.py banding): a saturated device
            # that can drain itself recovers faster than one that sheds
            tags["migration"] = True
        if any(
            getattr(e, "warmup_stats", None) is not None
            and e.warmup_stats().get("state") != "fully_warm"
            for e in self.gen_engines.values()
        ):
            # warmup planner still compiling (executor/warmup.py): the
            # device serves, but router banding ranks it behind fully-warm
            # peers until its background compiles drain — a request routed
            # here may still hit an XLA compile stall.
            tags["warming"] = True
        # Prefix-locality routing inputs (routing/prefix.py + router.py):
        # the resident-chain digest, the live admission-queue depth, and
        # the measured prefill cost — refreshed on every discovery tick.
        # tags_at stamps the refresh so routing/limits.py can de-rank a
        # wedged device whose tags went stale (ROUTE_TAG_TTL_S).
        digest = self._prefix_digest_tag()
        if digest is not None:
            tags["prefix_digest"] = digest
        qd = sum(
            float(getattr(e, "queue_depth", lambda: 0)() or 0)
            for e in self.gen_engines.values()
        )
        tags["queue_depth"] = qd
        pc = self._prefill_cost_tag()
        if pc is not None:
            tags["prefill_us_per_tok"] = round(pc, 2)
        if self.transfer_addr:
            # peers dial this for PrefixFetch (and remote migration)
            tags["transfer_addr"] = self.transfer_addr
        tags["tags_at"] = time.time()
        self.catalog.upsert_device(
            self.device_id,
            name=self.device_id,
            addr=self.advertise_addr,
            online=True,
            tags=tags,
        )
        for m in self.gen_engines:
            self.catalog.upsert_model(m, kind="llm")
        for m in self.embed_engines:
            self.catalog.upsert_model(m, kind="embed")
        self.catalog.sync_device_models(self.device_id, models)

    def engines_info(self) -> dict[str, Any]:
        info: dict[str, Any] = {}
        engines = dict(self.gen_engines)
        if self.zoo is not None:
            # zoo residents that were swapped in after boot report like any
            # other engine; parked models are /v1/debug/zoo territory
            for name in self.zoo.resident_models():
                try:
                    engines.setdefault(name, self.zoo.get(name))
                except (KeyError, RuntimeError):
                    pass
        for name, e in engines.items():
            p50, p95, n = e.ttft_percentiles()
            info[name] = {
                "kind": "generate",
                "slots_in_use": e.slots_in_use(),
                "max_slots": e.max_slots,
                "total_tokens": e.total_tokens,
                "total_requests": e.total_requests,
                "total_errors": e.total_errors,
                "tps_10s": round(e.current_tps(), 1),
                "ttft_p50_ms": round(p50, 1),
                "ttft_p95_ms": round(p95, 1),
                "decode_compact": e.decode_compact,
                "stalled": e.stalled,
                "prefix_cache": e.prefix_cache_stats(),
                # engine-loop wall-clock by phase since boot (the serve
                # budget breakdown bench.py windows — here cumulative, so
                # operators can diff two dashboard snapshots)
                "phase_s": {
                    k: round(v, 1) for k, v in e.phase_budget().items()
                },
            }
            self.metrics.engine_slots_in_use.set(e.slots_in_use())
            self.metrics.engine_tps.set(e.current_tps())
            ss = getattr(e, "scheduler_stats", None)
            if ss is not None:
                st = ss()
                info[name]["scheduler"] = st
                self.metrics.sched_prefill_token_budget.set(
                    st.get("prefill_token_budget", 0.0)
                )
                self.metrics.sched_decode_occupancy.set(
                    st.get("decode_batch_occupancy", 0.0)
                )
                prev = self._sched_starved.get(name, 0.0)
                cur = float(st.get("starved_rounds", 0.0))
                if cur > prev:
                    self.metrics.sched_starved_rounds.inc(cur - prev)
                self._sched_starved[name] = cur
            sps = getattr(e, "speculation_stats", None)
            if sps is not None:
                sp = sps()
                info[name]["speculation"] = sp
                self.metrics.spec_accept_rate.labels(engine=name).set(
                    sp.get("accept_rate", 0.0)
                )
                self.metrics.spec_tok_per_call.labels(engine=name).set(
                    sp.get("tok_per_call", 0.0)
                )
                prev_c = self._spec_counts.get(name, {})
                for key, counter in (
                    ("drafted_tokens", self.metrics.spec_drafted_tokens),
                    ("emitted_tokens", self.metrics.spec_emitted_tokens),
                ):
                    cur_c = float(sp.get(key, 0.0))
                    if cur_c > prev_c.get(key, 0.0):
                        counter.labels(engine=name).inc(
                            cur_c - prev_c.get(key, 0.0)
                        )
                self._spec_counts[name] = {
                    "drafted_tokens": float(sp.get("drafted_tokens", 0.0)),
                    "emitted_tokens": float(sp.get("emitted_tokens", 0.0)),
                }
            mst = getattr(e, "memory_stats", None)
            if mst is not None:
                ms = mst()
                if ms.get("enabled"):
                    info[name]["memory"] = ms
                    self.metrics.kv_pool_headroom.labels(engine=name).set(
                        ms.get("headroom", 1.0)
                    )
                    prev_p = self._pool_counts.get(name, {})
                    for key, counter in (
                        ("preempted_total", self.metrics.kv_preempted),
                        ("restored_total", self.metrics.kv_restored),
                        ("shed_total", self.metrics.kv_shed),
                    ):
                        cur_p = float(ms.get(key, 0.0))
                        if cur_p > prev_p.get(key, 0.0):
                            counter.labels(engine=name).inc(
                                cur_p - prev_p.get(key, 0.0)
                            )
                    self._pool_counts[name] = {
                        k: float(ms.get(k, 0.0))
                        for k in ("preempted_total", "restored_total", "shed_total")
                    }
            pst = getattr(e, "paging_stats", None)
            if pst is not None:
                ps = pst()
                info[name]["paging"] = ps
                self.metrics.kv_blocks_used.labels(engine=name).set(
                    ps.get("blocks_used", 0.0)
                )
                self.metrics.kv_block_sharing.labels(engine=name).set(
                    ps.get("sharing_ratio", 1.0)
                )
                self.metrics.kv_block_leaks.labels(engine=name).set(
                    ps.get("leaks", 0.0)
                )
                prev_b = self._paging_counts.get(name, 0.0)
                cur_b = float(ps.get("cow_copies_total", 0.0))
                if cur_b > prev_b:
                    self.metrics.kv_cow_copies.labels(engine=name).inc(
                        cur_b - prev_b
                    )
                self._paging_counts[name] = cur_b
            mgs = getattr(e, "migration_stats", None)
            if mgs is not None:
                mg = mgs()
                if mg.get("enabled"):
                    info[name]["migration"] = mg
                    prev_m = self._migration_counts.get(name, {})
                    for key, counter in (
                        ("migrated_out_total", self.metrics.kv_migrated_out),
                        ("migrated_in_total", self.metrics.kv_migrated_in),
                        ("migrate_out_bytes_total", self.metrics.kv_migrate_bytes),
                    ):
                        cur_m = float(mg.get(key, 0.0))
                        if cur_m > prev_m.get(key, 0.0):
                            counter.labels(engine=name).inc(
                                cur_m - prev_m.get(key, 0.0)
                            )
                    self._migration_counts[name] = {
                        k: float(mg.get(k, 0.0))
                        for k in (
                            "migrated_out_total",
                            "migrated_in_total",
                            "migrate_out_bytes_total",
                        )
                    }
            pts = getattr(e, "prefix_tier_stats", None)
            if pts is not None:
                pt = pts()
                if pt.get("enabled"):
                    info[name]["prefix_tier"] = pt
                    prev_t = self._prefix_tier_counts.get(name, {})
                    for key, counter in (
                        ("exports_total", self.metrics.prefix_tier_exports.labels(engine=name)),
                        ("imports_total", self.metrics.prefix_tier_imports.labels(engine=name)),
                        ("import_rejects_total", self.metrics.prefix_tier_rejects.labels(engine=name)),
                        ("export_bytes_total", self.metrics.prefix_tier_bytes.labels(engine=name, direction="out")),
                        ("import_bytes_total", self.metrics.prefix_tier_bytes.labels(engine=name, direction="in")),
                    ):
                        cur_t = float(pt.get(key, 0.0))
                        if cur_t > prev_t.get(key, 0.0):
                            counter.inc(cur_t - prev_t.get(key, 0.0))
                    self._prefix_tier_counts[name] = {
                        k: float(pt.get(k, 0.0))
                        for k in (
                            "exports_total",
                            "imports_total",
                            "import_rejects_total",
                            "export_bytes_total",
                            "import_bytes_total",
                        )
                    }
            pfs = getattr(e, "perf_stats", None)
            if pfs is not None:
                pf = pfs()
                info[name]["perf"] = pf
                gp = pf.get("goodput") or {}
                rl = pf.get("roofline") or {}
                self.metrics.goodput_tok_per_s.labels(engine=name).set(
                    gp.get("goodput_tok_per_s", 0.0)
                )
                self.metrics.goodput_ratio.labels(engine=name).set(
                    gp.get("goodput_ratio", 1.0)
                )
                self.metrics.decode_mfu.labels(engine=name).set(
                    rl.get("decode_mfu", 0.0)
                )
                self.metrics.decode_mbu.labels(engine=name).set(
                    rl.get("decode_mbu", 0.0)
                )
                # per-tenant goodput (model zoo tenancy): gauges set
                # direct; shed counts advance by delta like every other
                # cumulative bridge. No tenants ⇒ empty dict ⇒ no series.
                tns = pf.get("tenants") or {}
                prev_ts = self._tenant_shed.get(name, {})
                cur_ts: dict[str, float] = {}
                for tenant, tgp in tns.items():
                    self.metrics.goodput_tok_per_s_tenant.labels(
                        engine=name, tenant=tenant
                    ).set(tgp.get("goodput_tok_per_s", 0.0))
                    self.metrics.goodput_ratio_tenant.labels(
                        engine=name, tenant=tenant
                    ).set(tgp.get("goodput_ratio", 1.0))
                    cur_shed = float(tgp.get("shed", 0.0))
                    cur_ts[tenant] = cur_shed
                    if cur_shed > prev_ts.get(tenant, 0.0):
                        self.metrics.tenant_shed_total.labels(
                            engine=name, tenant=tenant
                        ).inc(cur_shed - prev_ts.get(tenant, 0.0))
                self._tenant_shed[name] = cur_ts
                # sampled phase walls advance by delta, per (phase, bucket)
                prev_ph = self._perf_phase_s.get(name, {})
                cur_ph: dict[str, float] = {}
                for ph, rec_ in (pf.get("phases") or {}).items():
                    for bucket in ("host_s", "device_s", "wait_s"):
                        k = f"{ph}/{bucket}"
                        cur = float(rec_.get(bucket, 0.0))
                        cur_ph[k] = cur
                        if cur > prev_ph.get(k, 0.0):
                            self.metrics.perf_phase_seconds.labels(
                                engine=name, phase=ph,
                                bucket=bucket[:-2],
                            ).inc(cur - prev_ph.get(k, 0.0))
                self._perf_phase_s[name] = cur_ph
                # each ITL sample lands in the histogram exactly once
                drain = getattr(e, "drain_itl_samples", None)
                if drain is not None:
                    h = self.metrics.itl_seconds.labels(engine=name)
                    for v in drain():
                        h.observe(v)
            fst = getattr(e, "flight_stats", None)
            if fst is not None:
                fs = fst()
                info[name]["flight"] = fs
                by_det = (fs.get("anomaly") or {}).get("by_detector") or {}
                prev_a = self._anomaly_counts.get(name, {})
                for det, cur_a in by_det.items():
                    if float(cur_a) > prev_a.get(det, 0.0):
                        self.metrics.anomaly_dumps.labels(
                            engine=name, detector=det
                        ).inc(float(cur_a) - prev_a.get(det, 0.0))
                self._anomaly_counts[name] = {
                    det: float(v) for det, v in by_det.items()
                }
                wts = fs.get("watchdog_transitions") or {}
                prev_w = self._watchdog_counts.get(name, {})
                for state, cur_w in wts.items():
                    if float(cur_w) > prev_w.get(state, 0.0):
                        self.metrics.watchdog_transitions.labels(
                            engine=name, state=state
                        ).inc(float(cur_w) - prev_w.get(state, 0.0))
                self._watchdog_counts[name] = {
                    state: float(v) for state, v in wts.items()
                }
            wfs = getattr(e, "waterfall_stats", None)
            if wfs is not None:
                w = wfs()
                info[name]["waterfall"] = w
                # per-request stage walls are cumulative per engine+stage;
                # the counter advances by the delta between refreshes
                prev_l = self._latency_stage_s.get(name, {})
                cur_l: dict[str, float] = {}
                for stage, cur in (w.get("stage_s") or {}).items():
                    cur = float(cur)
                    cur_l[stage] = cur
                    if cur > prev_l.get(stage, 0.0):
                        self.metrics.latency_stage_seconds.labels(
                            engine=name, stage=stage
                        ).inc(cur - prev_l.get(stage, 0.0))
                self._latency_stage_s[name] = cur_l
            wls = getattr(e, "workload_stats", None)
            if wls is not None:
                info[name]["workload"] = wls()
        # Process-wide flight ring + compile ledger (telemetry/recorder.py
        # singletons shared by every engine in this process): events advance
        # by delta, drops are a gauge (perf_gate hard-fails >0), and each
        # fresh ledger entry feeds the compile histogram exactly once.
        rec = flight.get_recorder()
        cur_ev = float(rec.events_total())
        if cur_ev > self._flight_events:
            self.metrics.flight_events.inc(cur_ev - self._flight_events)
            self._flight_events = cur_ev
        self.metrics.flight_dropped.set(float(rec.dropped_events))
        for entry in flight.get_compile_ledger().drain_fresh():
            self.metrics.compile_seconds.labels(
                engine=self.device_id,
                phase=entry["phase"],
                hit="hit" if entry["hit"] else "miss",
            ).observe(float(entry["wall_s"]))
        if self.migration is not None:
            cst = self.migration.stats()
            self.metrics.kv_migration_headroom_delta.set(
                cst.get("headroom_delta", 0.0)
            )
            cur_r = float(cst.get("requeues_total", 0.0))
            if cur_r > self._migration_requeues:
                self.metrics.kv_migrate_requeues.inc(cur_r - self._migration_requeues)
                self._migration_requeues = cur_r
        for name, e in self.embed_engines.items():
            info[name] = {
                "kind": "embed",
                "total_inputs": e.total_inputs,
                "total_tokens": e.total_tokens,
            }
        return info

    # -- routes ------------------------------------------------------------

    def _register_routes(self) -> None:
        r = self.api.route
        r("GET", "/health", self.handle_health)
        r("GET", "/metrics", self.handle_metrics)

        # jobs + worker protocol
        r("POST", "/v1/jobs", self.jobs.handle_submit)
        r("GET", "/v1/jobs", self.jobs.handle_list)
        r("GET", "/v1/jobs/{id}", self.jobs.handle_get)
        r("DELETE", "/v1/jobs/{id}", self.jobs.handle_cancel)
        r("GET", "/v1/jobs/{id}/stream", self.jobs.handle_stream)
        r("POST", "/v1/jobs/claim", self.jobs.handle_claim)
        r("POST", "/v1/jobs/{id}/complete", self.jobs.handle_complete)
        r("POST", "/v1/jobs/{id}/fail", self.jobs.handle_fail)
        r("POST", "/v1/jobs/{id}/heartbeat", self.jobs.handle_heartbeat)
        r("POST", "/v1/workers/register", self.jobs.handle_worker_register)
        r("POST", "/v1/devices/offline", self.jobs.handle_devices_offline)

        # inference
        r("POST", "/v1/llm/request", self.inference.handle_llm_request)
        r("POST", "/v1/chat/completions", self.inference.handle_chat_completions)
        r("POST", "/v1/embeddings", self.inference.handle_embeddings)

        # catalog
        r("GET", "/v1/models", self.handle_models)
        r("POST", "/v1/models/sync", self.handle_models_sync)
        r("GET", "/v1/models/stats", self.handle_model_stats)
        r("GET", "/v1/devices", self.handle_devices)
        r("GET", "/v1/benchmarks", self.handle_benchmarks)

        # discovery
        r("POST", "/v1/discovery/run", self.handle_discovery_run)

        # observability / business
        r("GET", "/v1/traces", self.handle_traces)
        r("GET", "/v1/traces/{id}", self.handle_trace)
        r("GET", "/v1/dashboard", self.dashboard.handle_dashboard)
        r("GET", "/v1/costs/summary", self.handle_costs_summary)
        r("GET", "/v1/costs/balance", self.handle_costs_balance)
        r("POST", "/v1/feedback", self.handle_feedback)
        r("GET", "/v1/debug/health", self.dashboard.handle_health)
        r("GET", "/v1/debug/actions", self.dashboard.handle_actions)
        r("GET", "/v1/debug/capacity", self.dashboard.handle_capacity)
        r("POST", "/v1/debug/test", self.dashboard.handle_smoke_test)
        r("GET", "/v1/debug/flight", self.handle_debug_flight)
        r("GET", "/v1/debug/compiles", self.handle_debug_compiles)
        r("GET", "/v1/debug/warmup", self.handle_debug_warmup)
        r("GET", "/v1/debug/perf", self.handle_debug_perf)
        r("GET", "/v1/debug/zoo", self.handle_debug_zoo)
        r("GET", "/v1/debug/workload", self.handle_debug_workload)
        r("GET", "/v1/debug/constrain", self.handle_debug_constrain)
        r("GET", "/v1/debug/latency", self.handle_debug_latency)
        r("GET", "/v1/debug/prefix", self.handle_debug_prefix)
        r("GET", "/v1/debug/profile", self.handle_debug_profile)
        r("POST", "/v1/debug/profile", self.handle_debug_profile_start)

        # knowledge
        r("POST", "/v1/knowledge/ingest", self.handle_knowledge_ingest)

        # planner (manual trigger + status; periodic runs via _ticker)
        r("POST", "/v1/planner/run", self.handle_planner_run)
        r("GET", "/v1/planner/status", self.handle_planner_status)

    # -- small handlers ------------------------------------------------------

    def handle_health(self, req: Request, resp: Response) -> None:
        # Executor identity fields feed peer discovery: probes read platform/
        # chips/hbm_gb to tag the device and derive its limits (the analog of
        # the reference deriving limits from reported RAM, limits.go:124-160).
        # The prefix tier's dynamic fields ride along so HTTP-discovered
        # peers can score prefix locality and boot-warm from this device
        # (discovery copies them into the catalog tags): the resident-chain
        # digest and the gRPC address PrefixFetch answers on.
        body = {"status": "ok", "service": "llm-mcp-tpu", **self._device_identity()}
        digest = self._prefix_digest_tag()
        if digest:
            body["prefix_digest"] = digest
        if self.transfer_addr:
            body["transfer_addr"] = self.transfer_addr
        resp.write_json(body)

    def _device_identity(self) -> dict[str, Any]:
        # Platform/chips/HBM are static for the life of the process, and
        # /health is the hot probe target (peer discovery, subnet sweeps,
        # LB checks) — compute once.
        if self._identity_cache is not None:
            return self._identity_cache
        ident: dict[str, Any] = {"device_id": self.device_id}
        try:
            import jax

            devs = jax.devices()
            ident["platform"] = devs[0].platform
            ident["chips"] = len(devs)
            stats = getattr(devs[0], "memory_stats", lambda: None)()
            if stats and "bytes_limit" in stats:
                ident["hbm_gb"] = round(
                    len(devs) * stats["bytes_limit"] / (1 << 30), 1
                )
        except Exception:
            pass
        ident["engines"] = sorted(list(self.gen_engines) + list(self.embed_engines))
        self._identity_cache = ident
        return ident

    def handle_metrics(self, req: Request, resp: Response) -> None:
        self.engines_info()  # refresh engine slot/tps gauges at scrape time
        self.metrics.devices_online.set(
            len(self.catalog.list_devices(online_only=True))
        )
        data, ctype = self.metrics.render()
        resp.write_bytes(data, ctype)

    def _observe_span(self, span: tracing.Span) -> None:
        """Tracer observer → per-stage latency histograms. Keeps the span
        library metrics-free: the bridge lives here."""
        stage = _SPAN_STAGES.get(span.name) or (
            "rpc" if span.name.startswith("rpc.") else ""
        )
        if stage:
            self.metrics.stage_duration.labels(stage=stage).observe(span.duration_s)

    def handle_traces(self, req: Request, resp: Response) -> None:
        """Newest-first summaries of the completed-trace ring."""
        try:
            limit = int(req.query.get("limit") or 50)
        except ValueError:
            resp.write_error("limit must be an integer", 400)
            return
        resp.write_json(
            {"enabled": self.tracer.enabled, "traces": self.tracer.traces(limit=limit)}
        )

    def handle_trace(self, req: Request, resp: Response) -> None:
        trace_id = req.params["id"]
        spans = self.tracer.get_trace(trace_id)
        if not spans:
            resp.write_error("trace not found", 404)
            return
        resp.write_json({"trace_id": trace_id, "spans": spans})

    # -- flight recorder / compile ledger / profiler (doc/observability.md) --

    def handle_debug_flight(self, req: Request, resp: Response) -> None:
        """Live tail of the flight-recorder ring plus anomaly-dump history.
        `?limit=N` bounds the event tail, `?etype=X` filters by event type,
        `?dump=1` forces a journal dump (rate-limit bypassed) — the manual
        equivalent of an anomaly trigger, for capturing a healthy baseline."""
        try:
            limit = int(req.query.get("limit") or 100)
        except ValueError:
            resp.write_error("limit must be an integer", 400)
            return
        rec = flight.get_recorder()
        out: dict[str, Any] = {
            "recorder": rec.stats(),
            "events": rec.snapshot(limit=limit, etype=req.query.get("etype") or ""),
            "anomalies": {
                name: e.anomaly_history()
                for name, e in self.gen_engines.items()
                if getattr(e, "anomaly_history", None) is not None
            },
        }
        if req.query.get("dump") in ("1", "true", "yes"):
            out["dump_path"] = rec.dump("manual", detector="api", force=True)
        resp.write_json(out)

    def handle_debug_compiles(self, req: Request, resp: Response) -> None:
        """Queryable compile ledger: per-shape aggregates (costliest first)
        and the raw first-sighting entries behind llmtpu_compile_seconds."""
        try:
            limit = int(req.query.get("limit") or 100)
        except ValueError:
            resp.write_error("limit must be an integer", 400)
            return
        led = flight.get_compile_ledger()
        resp.write_json(
            {
                "stats": led.stats(),
                "table": led.table(),
                "entries": led.entries(limit=limit),
            }
        )

    def handle_debug_warmup(self, req: Request, resp: Response) -> None:
        """Warmup readiness per engine (executor/warmup.py): planner state
        (cold / first_token_ready / fully_warm), per-step plan status, and
        background-compile progress — plus the boot-time peer warm-fill
        outcome."""
        resp.write_json(
            {
                "engines": {
                    name: e.warmup_stats()
                    for name, e in self.gen_engines.items()
                    if getattr(e, "warmup_stats", None) is not None
                },
                "boot_prefix_imported": getattr(self, "_boot_prefix_imported", 0),
            }
        )

    def handle_debug_perf(self, req: Request, resp: Response) -> None:
        """Perf observatory (telemetry/perf.py) per engine: ITL/TPOT
        percentiles, the goodput split against the TTFT+ITL SLO — both
        engine-wide and per tenant ("tenants": goodput + shed counts per
        tenant id) — sampled per-phase {host, device, wait} attribution
        (TPU_PERF_SAMPLE), and the four-layout roofline (MFU/MBU vs
        TPU_PEAK_* chip peaks)."""
        engines = dict(self.gen_engines)
        if self.zoo is not None:
            for name in self.zoo.resident_models():
                try:
                    engines.setdefault(name, self.zoo.get(name))
                except (KeyError, RuntimeError):
                    pass
        out = {
            name: e.perf_stats()
            for name, e in engines.items()
            if getattr(e, "perf_stats", None) is not None
        }
        # per-tenant quota state (scheduler token buckets) joins each
        # engine's document so one fetch answers "who is being throttled
        # and why" — ledger (finished) and bucket (admission) side by side
        for name, e in engines.items():
            ss = getattr(e, "scheduler_tenant_stats", None)
            if ss is not None and name in out:
                out[name]["tenant_quotas"] = ss()
        resp.write_json(out)

    def handle_debug_zoo(self, req: Request, resp: Response) -> None:
        """Model zoo residency (executor/zoo.py): per-model
        resident/parked state, the HBM partition (weight bytes from the
        zoo census, KV bytes from each resident engine's pool), swap
        counters and last swap latencies. `{"enabled": false}` when no
        zoo is configured (TPU_ZOO_MODELS unset)."""
        if self.zoo is None:
            resp.write_json({"enabled": False})
            return
        st = self.zoo.stats()
        st["enabled"] = True
        resp.write_json(st)

    def handle_debug_workload(self, req: Request, resp: Response) -> None:
        """Workload capture (telemetry/workload.py): the process-shared
        ring's health plus its newest records. `?limit=N` bounds the record
        tail; `?dump=PATH` journals the whole ring to PATH as replayable
        JSONL (the manual equivalent of streaming via TPU_WORKLOAD_TRACE)."""
        try:
            limit = int(req.query.get("limit") or 100)
        except ValueError:
            resp.write_error("limit must be an integer", 400)
            return
        wl = workload.get_workload()
        out: dict[str, Any] = {
            "workload": wl.stats(),
            "records": wl.snapshot(limit=limit),
        }
        dump_path = (req.query.get("dump") or "").strip()
        if dump_path:
            try:
                out["dumped"] = wl.dump(dump_path)
                out["dump_path"] = dump_path
            except OSError as e:
                resp.write_error(f"dump failed: {e}", 400)
                return
        resp.write_json(out)

    def handle_debug_constrain(self, req: Request, resp: Response) -> None:
        """Grammar-constrained decoding (llm_mcp_tpu/constrain) per engine:
        kill-switch state (TPU_CONSTRAIN), request/token/illegal counters,
        schema validity, host mask cost per token, the spec-composition
        accept rate, and the compile cache's hit/miss/eviction + mask-memo
        stats (TPU_CONSTRAIN_CACHE)."""
        engines = dict(self.gen_engines)
        if self.zoo is not None:
            for name in self.zoo.resident_models():
                try:
                    engines.setdefault(name, self.zoo.get(name))
                except (KeyError, RuntimeError):
                    pass
        resp.write_json(
            {
                name: e.constrain_stats()
                for name, e in engines.items()
                if getattr(e, "constrain_stats", None) is not None
            }
        )

    def handle_debug_latency(self, req: Request, resp: Response) -> None:
        """Latency waterfall per engine: the per-stage decomposition of
        every finished request's wall (admit_wait / shed / prefill_queue /
        prefill_compute / decode / stall / preempt — an exact partition),
        percentile windows, and the most recent per-request rows.
        `?limit=N` bounds the recent-row tail."""
        try:
            limit = int(req.query.get("limit") or 32)
        except ValueError:
            resp.write_error("limit must be an integer", 400)
            return
        resp.write_json(
            {
                name: {
                    **e.waterfall_stats(),
                    "recent": e.waterfall_recent(limit),
                }
                for name, e in self.gen_engines.items()
                if getattr(e, "waterfall_stats", None) is not None
            }
        )

    def handle_debug_prefix(self, req: Request, resp: Response) -> None:
        """Fleet prefix tier: the knobs, this device's advertised digest,
        route-outcome counters (local / fetch / miss and wire time), and
        each engine's export/import tallies — the one-stop answer to "is
        prefix-locality routing actually hitting?"."""
        from ..routing import prefix as prefix_fp

        resp.write_json(
            {
                "enabled": prefix_fp.prefix_route_enabled(),
                "fetch_min_tokens": prefix_fp.fetch_min_tokens(),
                "transfer_addr": self.transfer_addr,
                "route": self.route_prefix_stats(),
                "digest": self._prefix_digest_tag(),
                "engines": {
                    name: e.prefix_tier_stats()
                    for name, e in self.gen_engines.items()
                    if getattr(e, "prefix_tier_stats", None) is not None
                },
            }
        )

    def handle_debug_profile(self, req: Request, resp: Response) -> None:
        resp.write_json(
            {
                name: e.profile_status()
                for name, e in self.gen_engines.items()
                if getattr(e, "profile_status", None) is not None
            }
        )

    def handle_debug_profile_start(self, req: Request, resp: Response) -> None:
        """Arm a jax.profiler capture for the next N engine-loop steps:
        body {"engine": name?, "steps": N?, "trace_dir": path?}. Defaults to
        the sole generation engine; the engine thread starts/stops the
        capture at loop boundaries (engine._profile_tick)."""
        try:
            body = req.json() or {}
        except Exception:
            resp.write_error("invalid JSON body", 400)
            return
        candidates = {
            name: e
            for name, e in self.gen_engines.items()
            if getattr(e, "start_profile", None) is not None
        }
        if not candidates:
            resp.write_error("no profiling-capable engine", 404)
            return
        name = body.get("engine") or next(iter(candidates))
        eng = candidates.get(name)
        if eng is None:
            resp.write_error(f"unknown engine {name!r}", 404)
            return
        try:
            steps = int(body.get("steps") or 20)
        except (TypeError, ValueError):
            resp.write_error("steps must be an integer", 400)
            return
        status = eng.start_profile(steps, trace_dir=str(body.get("trace_dir") or ""))
        resp.write_json({"engine": name, **status})

    def handle_models(self, req: Request, resp: Response) -> None:
        models = self.catalog.list_models(kind=req.query.get("kind"))
        resp.write_json({"models": models})

    def handle_models_sync(self, req: Request, resp: Response) -> None:
        """Sync cloud models into the catalog (`handlers.go:3176-3287`).
        Without a cloud provider, re-registers local engine models."""
        self.register_local_device()
        synced = len(self.gen_engines) + len(self.embed_engines)
        cloud_synced = 0
        if self.cloud is not None:
            try:
                cloud_synced = sync_cloud_catalog(self.catalog, self.cloud)
            except Exception as e:
                resp.write_json(
                    {"status": "partial", "local": synced, "cloud_error": str(e)}, 502
                )
                return
        resp.write_json({"status": "ok", "local": synced, "cloud": cloud_synced})

    def handle_model_stats(self, req: Request, resp: Response) -> None:
        resp.write_json({"stats": self.catalog.model_stats()})

    def handle_devices(self, req: Request, resp: Response) -> None:
        devices = self.catalog.list_devices()
        for d in devices:
            d["models"] = self.catalog.device_models(d["id"])
            d["circuit"] = self.circuit.status(d["id"])
        resp.write_json({"devices": devices})

    def handle_benchmarks(self, req: Request, resp: Response) -> None:
        resp.write_json({"benchmarks": self.catalog.list_benchmarks()})

    def handle_discovery_run(self, req: Request, resp: Response) -> None:
        t0 = time.time()
        try:
            result = self.discovery.run()
            self.metrics.discovery_runs.labels(status="ok").inc()
            self.metrics.discovery_duration.observe(time.time() - t0)
            self.metrics.devices_online.set(
                len(self.catalog.list_devices(online_only=True))
            )
            resp.write_json({"status": "ok", **result})
        except Exception as e:
            self.metrics.discovery_runs.labels(status="error").inc()
            resp.write_error(f"discovery failed: {e}", 500)

    def handle_costs_summary(self, req: Request, resp: Response) -> None:
        since = req.query.get("since")
        try:
            since_f = float(since) if since else None
        except ValueError:
            resp.write_error("since must be a unix timestamp", 400)
            return
        resp.write_json({"costs": self.catalog.costs_summary(since=since_f)})

    def handle_costs_balance(self, req: Request, resp: Response) -> None:
        if self.cloud is None:
            resp.write_error("no cloud provider configured", 503)
            return
        try:
            bal = self.cloud.balance()
            if bal.get("balance_usd") is not None:
                self.metrics.openrouter_balance.set(bal["balance_usd"])
            resp.write_json(bal)
        except Exception as e:
            resp.write_error(f"balance query failed: {e}", 502)

    def handle_feedback(self, req: Request, resp: Response) -> None:
        body = req.json()
        model = str(body.get("model") or "")
        rating = body.get("rating")
        if not model or rating not in ("up", "down", 1, -1, "+1", "-1"):
            resp.write_error("model and rating (up|down) required", 400)
            return
        self.catalog.record_feedback(model, up=rating in ("up", 1, "+1"))
        resp.write_json({"status": "ok"})

    def handle_knowledge_ingest(self, req: Request, resp: Response) -> None:
        """Proxy to LightRAG / mem0 (`handlers.go:2829-2946`)."""
        body = req.json()
        text = str(body.get("text") or "")
        target = str(body.get("target") or "lightrag")
        import httpx

        if target == "mem0":
            if not self.cfg.mem0_url:
                resp.write_error("MEM0_URL not configured", 503)
                return
            if len(text) < 10:
                resp.write_error("text too short for memory (min 10 chars)", 400)
                return
            try:
                r = httpx.post(
                    f"{self.cfg.mem0_url.rstrip('/')}/v1/memories/",
                    json={"messages": [{"role": "user", "content": text}],
                          "user_id": str(body.get("user_id") or "default")},
                    timeout=30.0,
                )
                resp.write_bytes(r.content, "application/json", r.status_code)
            except Exception as e:
                resp.write_error(f"mem0 unreachable: {e}", 502)
            return
        if not self.cfg.lightrag_url:
            resp.write_error("LIGHTRAG_URL not configured", 503)
            return
        if len(text) < 100:
            resp.write_error("text too short for ingestion (min 100 chars)", 400)
            return
        meta = body.get("metadata") or {}
        if meta:
            header = " | ".join(f"{k}: {v}" for k, v in meta.items())
            text = f"[{header}]\n\n{text}"
        headers = {}
        if self.cfg.lightrag_api_key:
            headers["X-API-Key"] = self.cfg.lightrag_api_key
        try:
            r = httpx.post(
                f"{self.cfg.lightrag_url.rstrip('/')}/documents/text",
                json={"text": text}, headers=headers, timeout=60.0,
            )
            resp.write_bytes(r.content, "application/json", r.status_code)
        except Exception as e:
            resp.write_error(f"lightrag unreachable: {e}", 502)

    def handle_planner_run(self, req: Request, resp: Response) -> None:
        resp.write_json({"status": "ok", "result": self.planner.run_once()})

    def handle_planner_status(self, req: Request, resp: Response) -> None:
        resp.write_json(
            {
                "runs": self.planner.runs,
                "last_run": self.planner.last_run,
                "last_result": self.planner.last_result,
                "interval_s": self.cfg.planner_interval_s,
            }
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self, host: str = "0.0.0.0", port: int = 8080) -> "CoreServer":
        self.api.serve(host, port)
        if not self.advertise_addr:
            self.advertise_addr = f"{host}:{self.api.port}"
        # Peers of this fleet serve on the same port we do: probe it, not
        # the default (slice-metadata hosts, port-less static endpoints,
        # subnet sweeps all derive their target port from this list).
        # TPU_EXTRA_PORTS widens the sweep for fleets with mixed ports
        # (the OLLAMA_PORTS pattern): comma-separated, own port probed first.
        ports = [self.api.port]
        for tok in os.environ.get("TPU_EXTRA_PORTS", "").split(","):
            tok = tok.strip()
            if not tok:
                continue
            try:
                p = int(tok)
            except ValueError:
                log.warning("TPU_EXTRA_PORTS: ignoring non-integer %r", tok)
                continue
            if 0 < p < 65536 and p not in ports:
                ports.append(p)
        self.discovery.ports = ports
        # Cold-start path (doc/performance.md "Cold start & warmup"):
        # critical-prefix AOT compiles run synchronously before the device
        # registers — no request can route here and hit a cold compile —
        # then registration advertises `warming` while the background zoo
        # fills in, then peer warm-fill pulls the fleet's hottest prefix
        # chains so the first shared-prefix request decodes from fetched
        # blocks. TPU_WARMUP=0 / TPU_BOOT_PREFILL_PEERS=0 skip each leg.
        self.boot_warmup()
        # register AFTER the addr is known so peers can proxy to us
        self.register_local_device()
        self._boot_prefix_imported = self.boot_prefix_warm()
        self.limits.apply_specs()
        if self.migration is not None:
            self.migration.start()
        # background tickers: limits re-apply + discovery (main.go:56-67,101-112)
        t = threading.Thread(target=self._ticker, name="core-tickers", daemon=True)
        t.start()
        self._bg_threads.append(t)
        log.info("core server on %s:%d", host, self.api.port)
        return self

    def _ticker(self) -> None:
        last_limits = 0.0
        last_disc = 0.0
        while not self._bg_stop.wait(1.0):
            now = time.time()
            if now - last_limits >= self.cfg.device_limits_interval_s:
                last_limits = now
                try:
                    self.limits.apply_specs()
                except Exception:
                    log.exception("limits re-apply failed")
            if now - last_disc >= self.cfg.discovery_interval_s:
                last_disc = now
                try:
                    self.discovery.run()
                except Exception:
                    log.exception("periodic discovery failed")
            try:
                self.planner.maybe_run(now)
            except Exception:
                log.exception("planner tick failed")
            try:
                self._check_engine_stalls()
            except Exception:
                log.exception("engine stall check failed")

    def _check_engine_stalls(self) -> None:
        """Map a wedged accelerator to device state: while any local engine's
        loop is stalled, the self-device goes OFFLINE (its running jobs'
        leases reset so queue work re-routes — offline_handler.go:12-38
        analog) and the circuit records failures so sync routing fails over
        to other devices/cloud. Recovery flips it back online."""
        if not self.gen_engines or not self.device_id:
            return
        stalled = [n for n, e in self.gen_engines.items() if e.stalled]
        row = self.catalog.get_device(self.device_id)
        online = bool(row and row["online"])
        if stalled and online:
            log.error("local engines stalled (%s): marking %s offline",
                      ", ".join(stalled), self.device_id)
            self.catalog.set_device_online(self.device_id, False)
            self.router.circuit.record(self.device_id, ok=False)
            self.queue.requeue_device_jobs([self.device_id])
            self._stall_offlined = True
        elif not stalled and getattr(self, "_stall_offlined", False):
            # Recovery does NOT flip the device back itself: another path
            # (operator /v1/devices/offline, worker connection-failure
            # reports) may have offlined it during the stall window, and
            # re-onlining here would override that. The periodic discovery
            # tick re-registers the healthy self-device online on its own
            # cadence (register_local_device via Runner.run).
            self._stall_offlined = False

    def shutdown(self) -> None:
        self.tracer.remove_observer(self._observe_span)
        self._bg_stop.set()
        if self.migration is not None:
            self.migration.stop()
        self.api.shutdown()
        for e in self.gen_engines.values():
            e.shutdown()
        if self.zoo is not None:
            self.zoo.shutdown()
        self.db.close()
