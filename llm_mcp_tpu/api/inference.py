"""OpenAI-compatible sync inference surface: chat completions + embeddings,
and the smart-routed async `/v1/llm/request`.

Parity map (reference):
  - POST /v1/chat/completions: `core/internal/api/handlers.go:2087-2587` —
    but where the reference proxies Ollama NDJSON and re-chunks it into SSE
    (the token loop lives outside the repo), here the SSE frames come
    straight out of the in-process TPU decode loop.
  - POST /v1/embeddings: `handlers.go:1821-2078` — in-process encoder with
    exact Matryoshka `dimensions` truncation instead of the client-side
    fallback (`handlers.go:2063-2078`).
  - smart model selection when model=="" via model_rankings scoring:
    `handlers.go:2121-2159,3040-3144`.
  - POST /v1/llm/request: `handlers.go:645-697` — route, quality deadline,
    enqueue, 202.
  - `<think>` splitting into a reasoning field: `worker/llm_worker/main.py:207-219`.
  - cost + stats recording: `handlers.go:2608-2634,3147-3171`.

Remote TPU devices (another executor process found by discovery) are served
by proxying the same OpenAI-shaped request to the device's own HTTP address
— the analog of the reference's Ollama proxy hop, with circuit-breaker
bookkeeping on failures (`handlers.go:1899-1931`).
"""

from __future__ import annotations

import json
import logging
import os
import sqlite3
import time
import uuid
from typing import Any

from ..executor import EmbeddingEngine, GenerationEngine
from ..routing import Router, quality_deadline_s
from ..state.catalog import Catalog
from ..state.queue import JobQueue
from ..telemetry import Metrics, tracing
from ..utils.tokens import messages_to_prompt, split_think
from .http import Request, Response

log = logging.getLogger("inference")

CHAT_PROXY_TIMEOUT_S = 120.0
EMBED_PROXY_TIMEOUT_S = 120.0
EMBED_RETRIES = 3

# Tenancy (model zoo): the header whose value becomes GenRequest.tenant —
# per-tenant quotas, goodput ledgers and 429s all key off it. Operators
# fronting with an API-key gateway point TPU_TENANT_HEADER at their key
# header. Dynamic (read per request) so a live process can be re-keyed.
DEFAULT_TENANT_HEADER = "X-Tenant-Id"


def request_tenant(req: Request) -> str:
    """The request's tenant id, "" when the header is absent (unmetered —
    the single-tenant path touches none of the tenancy machinery)."""
    header = os.environ.get("TPU_TENANT_HEADER", "") or DEFAULT_TENANT_HEADER
    return (req.headers.get(header) or "").strip()


def parse_constraints(
    body: dict, n_vocab: int, bias_max: int
) -> tuple[dict | None, list | None, str | None]:
    """Distill the OpenAI-style structured-output surface into the engine's
    constraint spec: ``(constraint, logit_bias, error)``.

    - ``response_format``: ``json_object`` / ``json_schema`` (OpenAI), plus
      the ``regex`` and ``choice`` extensions (constrain/schema.py).
    - ``tools`` + ``tool_choice``: a FORCED tool call ("required" or a
      named function) becomes a json_schema constraint over the call
      object ``{"name": ..., "arguments": <parameters schema>}``;
      "auto"/"none"/absent leaves the model unconstrained.
    - ``logit_bias``: OpenAI token-id→bias map, values clamped to ±100;
      out-of-range ids and oversize maps are request errors (400), never
      silent truncation — a dropped bias entry would be an invisible
      behavior change.

    ``error`` is a 400-worthy message; both other slots are None then."""
    constraint: dict | None = None
    rf = body.get("response_format")
    if rf is not None:
        if not isinstance(rf, dict):
            return None, None, "response_format must be an object"
        typ = rf.get("type")
        if typ in (None, "text"):
            pass
        elif typ == "json_object":
            constraint = {"type": "json_object"}
        elif typ == "json_schema":
            js = rf.get("json_schema")
            schema = (
                js.get("schema") if isinstance(js, dict) else rf.get("schema")
            )
            if not isinstance(schema, (dict, bool)):
                return None, None, (
                    "response_format.json_schema requires a schema object"
                )
            constraint = {"type": "json_schema", "schema": schema}
        elif typ == "regex":
            pat = rf.get("pattern")
            if not isinstance(pat, str) or not pat:
                return None, None, "response_format.regex requires a pattern"
            constraint = {"type": "regex", "pattern": pat}
        elif typ == "choice":
            ch = rf.get("choices")
            if (
                not isinstance(ch, list)
                or not ch
                or not all(isinstance(c, str) and c for c in ch)
            ):
                return None, None, (
                    "response_format.choice requires non-empty string choices"
                )
            constraint = {"type": "choice", "choices": ch}
        else:
            return None, None, f"unsupported response_format type {typ!r}"
    tools = body.get("tools")
    tc = body.get("tool_choice")
    if tools is not None and tc not in (None, "none", "auto"):
        if not isinstance(tools, list) or not tools:
            return None, None, "tools must be a non-empty list"
        fns: dict[str, Any] = {}
        for t in tools:
            fn = t.get("function") if isinstance(t, dict) else None
            if not isinstance(fn, dict) or not fn.get("name"):
                return None, None, "each tool requires function.name"
            fns[str(fn["name"])] = fn.get("parameters")
        if isinstance(tc, dict):
            name = (tc.get("function") or {}).get("name")
            if name not in fns:
                return None, None, f"tool_choice names unknown tool {name!r}"
            fns = {name: fns[name]}
        elif tc != "required":
            return None, None, f"unsupported tool_choice {tc!r}"
        calls = [
            {
                "type": "object",
                "properties": {
                    "name": {"const": nm},
                    "arguments": params if params is not None else True,
                },
            }
            for nm, params in fns.items()
        ]
        constraint = {
            "type": "json_schema",
            "schema": calls[0] if len(calls) == 1 else {"anyOf": calls},
        }
    bias: list | None = None
    lb = body.get("logit_bias")
    if lb is not None:
        if not isinstance(lb, dict):
            return None, None, "logit_bias must map token ids to biases"
        if len(lb) > bias_max:
            return None, None, (
                f"logit_bias supports at most {bias_max} entries "
                "(LLM_MCP_TPU_CN_BIAS_MAX)"
            )
        bias = []
        for k, v in lb.items():
            try:
                tid, val = int(k), float(v)
            except (TypeError, ValueError):
                return None, None, f"invalid logit_bias entry {k!r}"
            if n_vocab and not (0 <= tid < n_vocab):
                return None, None, (
                    f"logit_bias token id {tid} out of range [0, {n_vocab})"
                )
            bias.append([tid, max(-100.0, min(100.0, val))])
    return constraint, bias, None


class InferenceAPI:
    def __init__(
        self,
        *,
        catalog: Catalog,
        queue: JobQueue,
        router: Router,
        metrics: Metrics,
        device_id: str = "tpu-local",
        gen_engines: dict[str, GenerationEngine] | None = None,
        embed_engines: dict[str, EmbeddingEngine] | None = None,
        cloud: Any = None,  # providers.CloudClient | None
        prefix_fetch: Any = None,  # CoreServer.maybe_prefix_fetch | None
        zoo: Any = None,  # executor.zoo.ModelZoo | None
    ):
        self.catalog = catalog
        self.queue = queue
        self.router = router
        self.metrics = metrics
        self.device_id = device_id
        self.gen_engines = gen_engines or {}
        self.embed_engines = embed_engines or {}
        self.cloud = cloud
        self.prefix_fetch = prefix_fetch
        self.zoo = zoo

    # -- helpers -----------------------------------------------------------

    def _local_gen(self, model: str) -> GenerationEngine | None:
        if model in self.gen_engines:
            return self.gen_engines[model]
        if self.zoo is not None and model in self.zoo.models():
            # zoo-managed model: resident engines return instantly; a
            # parked one pays its swap-in here, on the request thread —
            # the cold model's first token INCLUDES the swap, which is
            # exactly the latency the bench zoo_sweep measures
            try:
                return self.zoo.get(model)
            except (KeyError, RuntimeError):
                return None
        return None

    def _local_embed(self, model: str) -> EmbeddingEngine | None:
        return self.embed_engines.get(model)

    # accuracy level → (accuracy weight, cost factor), handlers.go:3051-3061
    _ACCURACY_WEIGHTS = {
        "low": (0.3, 3.0),
        "medium": (0.6, 1.5),
        "high": (0.9, 0.5),
        "critical": (1.0, 0.0),
    }

    def _select_model_smart(
        self,
        category: str = "general",
        accuracy: str = "medium",
        max_cost_usd: float = 0.0,
        messages: list | None = None,
        max_tokens: int = 512,
    ) -> str:
        """model=="" → best ranked model by category score × accuracy weight
        − cost factor × log-price tier (`handlers.go:3040-3144`): candidates
        failing the context fit or the caller's cost cap are skipped; a model
        unranked in the requested category falls back to its average score
        across categories, then to 50."""
        import math

        # estimated input tokens ≈ chars/4 (handlers.go:3042-3048)
        total_chars = 0
        for m in messages or []:
            c = m.get("content") if isinstance(m, dict) else None
            if isinstance(c, str):
                total_chars += len(c)
        est_tokens = total_chars / 4.0

        acc_weight, cost_factor = self._ACCURACY_WEIGHTS.get(
            accuracy, self._ACCURACY_WEIGHTS["medium"]
        )
        rows = self.catalog.db.query(
            """
            SELECT r.model_id,
                   MAX(CASE WHEN r.category = ? THEN r.score END) AS cat_score,
                   AVG(r.score) AS avg_score,
                   COALESCE(m.context_k, 0) AS context_k,
                   COALESCE(p.input_per_1m, 0) AS price_in,
                   COALESCE(p.output_per_1m, 0) AS price_out,
                   COALESCE(s.requests, 0) AS requests,
                   COALESCE(s.errors, 0) AS errors
            FROM model_rankings r
            LEFT JOIN models m ON m.id = r.model_id
            LEFT JOIN model_pricing p ON p.model_id = r.model_id
            LEFT JOIN model_stats s ON s.model_id = r.model_id
            GROUP BY r.model_id
            """,
            (category,),
        )
        best, best_score = "", -1e9
        for r in rows:
            ctx_k = r["context_k"] or 0
            if ctx_k > 0 and est_tokens > ctx_k * 1000:
                continue  # prompt won't fit the model's context
            # output side priced at the request's max_tokens (the reference
            # reuses the input estimate for both sides, handlers.go:3096 —
            # which underprices output-heavy requests by orders of magnitude)
            est_cost = (est_tokens / 1e6) * (r["price_in"] or 0) + (
                max(max_tokens, 0) / 1e6
            ) * (r["price_out"] or 0)
            if max_cost_usd > 0 and est_cost > max_cost_usd:
                continue
            cat_score = r["cat_score"]  # NULL (not 0.0) means unranked here
            if cat_score is None:
                cat_score = r["avg_score"] if r["avg_score"] is not None else 50.0
            # log-scaled input-price tier: cheap models win at low accuracy
            # regardless of prompt length (handlers.go:3115-3122)
            price_in = r["price_in"] or 0.0
            price_tier = math.log10(price_in * 1000 + 1) * 10 if price_in > 0 else 0.0
            # observed success rate multiplies the quality term — beyond the
            # reference formula: a model whose backend is failing most
            # requests must shed smart-routed traffic even if well ranked
            reqs = r["requests"] or 0
            success = (reqs - (r["errors"] or 0)) / reqs if reqs else 1.0
            score = cat_score * acc_weight * success - cost_factor * price_tier
            if score > best_score:
                best, best_score = r["model_id"], score
        if best:
            return best
        if rows:
            # ranked models existed but every one was filtered (context fit
            # or the caller's cost cap) — fail the selection like the
            # reference does ("no suitable model found",
            # handlers.go:3130-3132) rather than silently handing back a
            # model that violates the filters
            return ""
        # no rankings at all: any local llm from the catalog
        models = self.catalog.list_models(kind="llm")
        for m in models:
            if self._local_gen(m["id"]) is not None:
                return m["id"]
        return models[0]["id"] if models else ""

    # -- chat completions --------------------------------------------------

    def handle_chat_completions(self, req: Request, resp: Response) -> None:
        try:
            body = req.json()
        except json.JSONDecodeError:
            resp.write_error("invalid JSON body", 400)
            return
        model = str(body.get("model") or "")
        messages = body.get("messages") or []
        if not isinstance(messages, list) or not messages:
            resp.write_error("messages required", 400)
            return
        stream = bool(body.get("stream", False))
        try:
            raw_max = body.get("max_tokens", body.get("max_completion_tokens"))
            max_tokens = int(raw_max) if raw_max is not None else 512
            temperature = float(body.get("temperature", 0.7))
            top_p = float(body.get("top_p", 1.0))
        except (TypeError, ValueError) as e:
            resp.write_error(f"invalid numeric parameter: {e}", 400)
            return
        if max_tokens < 1:
            resp.write_error("max_tokens must be >= 1", 400)
            return
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]

        if not model:
            # smart selection surface: headers override body fields
            # (handlers.go:2122-2152); the chosen model is echoed back in
            # X-Selected-Model
            task_type = (
                req.headers.get("X-Task-Type")
                or str(body.get("task_type") or "")
                or "general"
            )
            accuracy = (
                req.headers.get("X-Accuracy")
                or str(body.get("accuracy") or "")
                or "medium"
            )
            try:
                max_cost = float(
                    req.headers.get("X-Max-Cost")
                    or body.get("max_cost_usd")
                    or 0.0
                )
            except (TypeError, ValueError):
                max_cost = 0.0
            model = self._select_model_smart(
                task_type, accuracy, max_cost, messages, max_tokens
            )
            if not model:
                resp.write_error("no model available", 503)
                return
            resp.extra_headers["X-Selected-Model"] = model
            # the proxy path forwards `body` — carry the selection so a
            # remote device serves exactly the advertised model instead of
            # re-selecting under its own defaults (handlers.go:2154-2159)
            body["model"] = model

        # slash names are the cloud namespace ("meta-llama/..." via
        # OpenRouter) — but only when no LOCAL engine carries the name: an
        # HF-style org/name id served from a local checkpoint dir
        # (models/configs.py:resolve_config) must not be shadowed by the
        # cloud heuristic
        if "/" in model and self._local_gen(model) is None:
            self._chat_cloud(req, resp, body, model, stream)
            return

        t0 = time.time()
        prompt = messages_to_prompt(messages)
        with tracing.get_tracer().span(
            "route", attrs={"model": model, "kind": "generate"}
        ) as rspan:
            engine = self._local_gen(model)
            dev = None if engine is not None else self.router.select_device(model, "generate")
            if engine is not None:
                rspan.set_attrs(
                    {"provider": "tpu", "device": self.device_id, "reason": "local-engine"}
                )
                # Fleet prefix tier: before dispatch, see whether this engine
                # (or a peer, via PrefixFetch) already holds the prompt's KV
                # prefix. Tokenizing here duplicates work the engine will do
                # at submit, but encode is microseconds against a prefill —
                # and it is what lets the route span carry the matched length.
                if self.prefix_fetch is not None:
                    outcome, matched = self.prefix_fetch(model, engine, prompt)
                    if outcome:
                        rspan.set_attr("prefix_matched_tokens", matched)
                        rspan.set_attr("prefix_outcome", outcome)
            else:
                rspan.set_attrs(
                    {
                        "provider": "tpu",
                        "device": dev["id"] if dev else "",
                        "reason": "device-select" if dev else "no-device",
                    }
                )
        if engine is None:
            if dev is not None and dev["id"] != self.device_id and dev["addr"]:
                self._chat_proxy(resp, dev, body, model, stream)
                return
            resp.write_error(f"model {model!r} not available on any device", 503)
            self.metrics.chat_requests.labels(model=model, provider="tpu", status="error").inc()
            return

        # Load shedding (executor/memory.py watermark + per-tenant quotas):
        # above the admission watermark, queueing more work only grows
        # every stream's latency — reject NOW with a drain estimate so
        # well-behaved clients back off (and the router's headroom tag
        # steers new traffic elsewhere). A request carrying a tenant id
        # also passes that tenant's token-bucket gate: an over-quota
        # tenant 429s HERE, per tenant, while in-quota tenants sail
        # through. admission_state is side-effect free; the shed is
        # recorded here, where the 429 actually happens. Embed engines
        # (and test stand-ins predating tenancy) lack the kwarg/method.
        tenant = request_tenant(req)
        adm = getattr(engine, "admission_state", None)
        if adm is None:
            shed, retry_after = False, 0.0
        elif tenant:
            try:
                shed, retry_after = adm(tenant=tenant)
            except TypeError:
                shed, retry_after = adm()
        else:
            shed, retry_after = adm()
        if shed:
            try:
                engine.note_shed(tenant=tenant)
            except TypeError:
                engine.note_shed()
            self.metrics.chat_requests.labels(
                model=model, provider="tpu", status="shed"
            ).inc()
            # (llmtpu_tenant_shed_total advances through the engines_info
            # delta bridge off the perf ledger note_shed just charged —
            # incrementing here too would double-count)
            resp.extra_headers["Retry-After"] = str(max(1, int(retry_after + 0.5)))
            resp.write_error(
                "server overloaded: admission watermark or tenant quota "
                "exceeded; retry after the indicated delay",
                429,
            )
            return

        try:
            priority = int(body.get("priority") or 0)
        except (TypeError, ValueError):
            priority = 0
        # structured-output surface: parsed AFTER engine resolution so the
        # vocab bound for logit_bias validation is the serving engine's
        cfg = getattr(engine, "cfg", None)
        constraint, logit_bias, cn_err = parse_constraints(
            body,
            int(getattr(cfg, "vocab_size", 0) or 0),
            int(getattr(engine, "cn_bias_max", 64)),
        )
        if cn_err is not None:
            resp.write_error(cn_err, 400)
            self.metrics.chat_requests.labels(
                model=model, provider="tpu", status="error"
            ).inc()
            return
        gen_kwargs = dict(
            max_tokens=max_tokens, temperature=temperature, top_p=top_p, stop=stop,
            priority=priority,
        )
        if tenant:
            # only metered requests carry the kwarg: the zero-tenant call
            # signature (and the GenRequest it builds) stays byte-identical
            gen_kwargs["tenant"] = tenant
        # same convention for constraints: unconstrained requests build a
        # byte-identical GenRequest
        if constraint is not None:
            gen_kwargs["constraint"] = constraint
        if logit_bias:
            gen_kwargs["logit_bias"] = logit_bias
        created = int(t0)
        cmpl_id = f"chatcmpl-{uuid.uuid4().hex[:24]}"

        if stream:
            self._chat_stream_local(resp, engine, model, prompt, gen_kwargs, cmpl_id, created, t0)
        else:
            self._chat_sync_local(resp, engine, model, prompt, gen_kwargs, cmpl_id, created, t0)

    def _chat_sync_local(self, resp, engine, model, prompt, gen_kwargs, cmpl_id, created, t0):
        with tracing.get_tracer().span("engine.generate", attrs={"model": model}) as sp:
            try:
                out = engine.generate(prompt, **gen_kwargs)
            except RuntimeError as e:
                sp.set_error(str(e))
                resp.write_error(str(e), 500)
                self.metrics.chat_requests.labels(model=model, provider="tpu", status="error").inc()
                self.router.circuit.record(self.device_id, ok=False)
                return
            sp.set_attrs(
                {
                    "prompt_tokens": out["usage"].get("prompt_tokens", 0),
                    "completion_tokens": out["usage"].get("completion_tokens", 0),
                    "finish_reason": out["finish_reason"],
                }
            )
        self.router.circuit.record(self.device_id, ok=True)
        usage = out["usage"]
        thinking, answer = split_think(out["text"])
        message: dict[str, Any] = {"role": "assistant", "content": answer}
        if thinking:
            message["reasoning"] = thinking
        resp.write_json(
            {
                "id": cmpl_id,
                "object": "chat.completion",
                "created": created,
                "model": model,
                "choices": [
                    {"index": 0, "message": message, "finish_reason": out["finish_reason"]}
                ],
                "usage": usage,
            }
        )
        self._record_chat(model, "tpu", usage, time.time() - t0, ok=True)

    def _chat_stream_local(self, resp, engine, model, prompt, gen_kwargs, cmpl_id, created, t0):
        resp.start_sse()
        base = {"id": cmpl_id, "object": "chat.completion.chunk", "created": created, "model": model}
        first = dict(base, choices=[{"index": 0, "delta": {"role": "assistant"}, "finish_reason": None}])
        if not resp.sse_data(first):
            return
        usage: dict[str, Any] = {}
        finish = "stop"
        ok = True
        ttft: float | None = None
        with tracing.get_tracer().span(
            "engine.generate", attrs={"model": model, "stream": True}
        ) as sp:
            for evt in engine.generate_stream(prompt, **gen_kwargs):
                if evt["type"] == "token":
                    if ttft is None:
                        ttft = time.time() - t0
                        self.metrics.chat_ttft.labels(model=model).observe(ttft)
                        sp.set_attr("ttft_ms", round(ttft * 1000.0, 1))
                    chunk = dict(
                        base,
                        choices=[{"index": 0, "delta": {"content": evt["text"]}, "finish_reason": None}],
                    )
                    if not resp.sse_data(chunk):
                        sp.set_attr("client_disconnected", True)
                        return  # client went away; engine keeps finishing the slot
                elif evt["type"] == "done":
                    usage = evt.get("usage", {})
                    finish = evt.get("finish_reason", "stop")
                elif evt["type"] == "error":
                    ok = False
                    sp.set_error(evt.get("error", ""))
                    resp.sse_data(dict(base, error={"message": evt.get("error", "")}))
                    break
            sp.set_attrs(
                {
                    "prompt_tokens": usage.get("prompt_tokens", 0),
                    "completion_tokens": usage.get("completion_tokens", 0),
                    "finish_reason": finish,
                }
            )
        final = dict(
            base, choices=[{"index": 0, "delta": {}, "finish_reason": finish}], usage=usage
        )
        resp.sse_data(final)
        resp.sse_data("[DONE]")
        self.router.circuit.record(self.device_id, ok=ok)
        self._record_chat(model, "tpu", usage, time.time() - t0, ok=ok)

    def _chat_proxy(self, resp: Response, dev: dict, body: dict, model: str, stream: bool) -> None:
        """Forward to a remote TPU device's own /v1/chat/completions —
        the reference's Ollama-device hop (`handlers.go:2427-2470`)."""
        import httpx

        url = f"http://{dev['addr']}/v1/chat/completions"
        # carry the trace across the device hop (remote serves its own root
        # span joined to this trace via the traceparent header)
        ctx = tracing.current_traceparent()
        headers = {"traceparent": ctx} if ctx else {}
        try:
            if stream:
                with httpx.stream(
                    "POST", url, json=body, headers=headers, timeout=CHAT_PROXY_TIMEOUT_S
                ) as r:
                    if r.status_code >= 400:
                        # surface the remote error as an error, not a 200 SSE
                        r.read()
                        self.router.circuit.record(dev["id"], ok=r.status_code < 500)
                        resp.write_bytes(r.content, "application/json", r.status_code)
                        return
                    resp.start_sse()
                    for line in r.iter_lines():
                        if line.startswith("data: "):
                            if not resp.sse_data(line[len("data: "):]):
                                break
                self.router.circuit.record(dev["id"], ok=True)
            else:
                r = httpx.post(url, json=body, headers=headers, timeout=CHAT_PROXY_TIMEOUT_S)
                resp.write_bytes(r.content, "application/json", r.status_code)
                self.router.circuit.record(dev["id"], ok=r.status_code < 500)
        except Exception as e:  # connection-class failure → breaker
            self.router.circuit.record(dev["id"], ok=False)
            self.metrics.chat_requests.labels(model=model, provider="tpu", status="error").inc()
            if not resp.started:
                resp.write_error(f"device {dev['id']} unreachable: {e}", 502)

    def _chat_cloud(self, req: Request, resp: Response, body: dict, model: str, stream: bool) -> None:
        if self.cloud is None:
            resp.write_error("no cloud provider configured", 503)
            return
        t0 = time.time()
        sp = tracing.current_span()
        if sp is not None:
            sp.set_attrs({"provider": "cloud", "model": model})
        try:
            if stream:
                resp.start_sse()
                usage = {}
                for frame in self.cloud.chat_stream(body):
                    if isinstance(frame, dict):
                        usage = frame.get("usage") or usage
                    if not resp.sse_data(frame):
                        break
                resp.sse_data("[DONE]")
                self._record_chat(model, "cloud", usage, time.time() - t0, ok=True)
            else:
                out = self.cloud.chat(body)
                resp.write_json(out)
                self._record_chat(model, "cloud", out.get("usage", {}), time.time() - t0, ok=True)
        except Exception as e:
            self.metrics.chat_requests.labels(model=model, provider="cloud", status="error").inc()
            if not resp.started:
                resp.write_error(f"cloud provider error: {e}", 502)

    def _record_chat(self, model: str, provider: str, usage: dict, dt: float, ok: bool) -> None:
        status = "ok" if ok else "error"
        self.metrics.chat_requests.labels(model=model, provider=provider, status=status).inc()
        self.metrics.chat_duration.labels(model=model, provider=provider).observe(dt)
        tin = int(usage.get("prompt_tokens") or 0)
        tout = int(usage.get("completion_tokens") or 0)
        if tin:
            self.metrics.chat_tokens.labels(model=model, provider=provider, direction="in").inc(tin)
        if tout:
            self.metrics.chat_tokens.labels(model=model, provider=provider, direction="out").inc(tout)
        try:
            cost = self.catalog.record_cost(model, provider, tin, tout)
            if cost:
                self.metrics.chat_cost_usd.labels(model=model, provider=provider).inc(cost)
            self.catalog.update_model_stats(
                model, tokens_in=tin, tokens_out=tout, cost_usd=cost,
                duration_ms=dt * 1000.0, error=not ok,
            )
        except sqlite3.ProgrammingError:
            # server shutdown closed the DB while this handler's stream was
            # still finishing — the client already has its [DONE]; dropping
            # the post-hoc stats row beats crashing the handler
            log.debug("stats recording skipped: database closed mid-shutdown")

    # -- embeddings --------------------------------------------------------

    def handle_embeddings(self, req: Request, resp: Response) -> None:
        try:
            body = req.json()
        except json.JSONDecodeError:
            resp.write_error("invalid JSON body", 400)
            return
        model = str(body.get("model") or "")
        raw_input = body.get("input")
        if isinstance(raw_input, str):
            texts = [raw_input]
        elif isinstance(raw_input, list) and all(isinstance(t, str) for t in raw_input):
            texts = raw_input
        else:
            resp.write_error("input must be a string or list of strings", 400)
            return
        if not texts:
            resp.write_error("input must not be empty", 400)
            return
        try:
            dimensions = body.get("dimensions")
            dimensions = int(dimensions) if dimensions else None
        except (TypeError, ValueError):
            resp.write_error("dimensions must be an integer", 400)
            return

        if not model:
            embeds = self.catalog.list_models(kind="embed")
            local = [m["id"] for m in embeds if m["id"] in self.embed_engines]
            model = local[0] if local else (embeds[0]["id"] if embeds else "")
        if not model:
            resp.write_error("no embedding model available", 503)
            return

        # same local-first rule as chat: a slash name only means "cloud"
        # when no local embedding engine carries it
        if "/" in model and self._local_embed(model) is None:
            self._embed_cloud(resp, model, texts, dimensions)
            return

        t0 = time.time()
        engine = self._local_embed(model)
        if engine is not None:
            vectors, ntok = engine.embed(texts, dimensions=dimensions)
            self._write_embeddings(resp, model, vectors, ntok)
            self.metrics.embedding_requests.labels(
                model=model, device=self.device_id, status="ok"
            ).inc()
            self.metrics.embedding_duration.labels(model=model).observe(time.time() - t0)
            self.metrics.embedding_input_tokens.labels(model=model).inc(ntok)
            return

        # remote devices: ≤3 attempts across devices with breaker updates
        # (`handlers.go:1899-1931`)
        import httpx

        last_err = "no device has the model"
        for _ in range(EMBED_RETRIES):
            dev = self.router.select_device(model, "embed")
            if dev is None or dev["id"] == self.device_id or not dev["addr"]:
                break
            try:
                r = httpx.post(
                    f"http://{dev['addr']}/v1/embeddings",
                    json={"model": model, "input": texts, "dimensions": dimensions},
                    timeout=EMBED_PROXY_TIMEOUT_S,
                )
                r.raise_for_status()
                self.router.circuit.record(dev["id"], ok=True)
                resp.write_bytes(r.content, "application/json")
                self.metrics.embedding_requests.labels(
                    model=model, device=dev["id"], status="ok"
                ).inc()
                return
            except Exception as e:
                last_err = str(e)
                self.router.circuit.record(dev["id"], ok=False)
                self.metrics.embedding_requests.labels(
                    model=model, device=dev["id"], status="error"
                ).inc()
        resp.write_error(f"embeddings unavailable for {model!r}: {last_err}", 503)

    def _embed_cloud(self, resp: Response, model: str, texts: list[str], dimensions: int | None) -> None:
        if self.cloud is None:
            resp.write_error("no cloud provider configured", 503)
            return
        try:
            out = self.cloud.embed(model, texts, dimensions)
            # Matryoshka client-side truncation fallback (`handlers.go:2063-2078`)
            if dimensions and out.get("data"):
                for item in out["data"]:
                    vec = item.get("embedding") or []
                    if len(vec) > dimensions:
                        import math

                        vec = vec[:dimensions]
                        norm = math.sqrt(sum(v * v for v in vec)) or 1.0
                        item["embedding"] = [v / norm for v in vec]
            resp.write_json(out)
        except Exception as e:
            resp.write_error(f"cloud embeddings error: {e}", 502)

    @staticmethod
    def _write_embeddings(resp: Response, model: str, vectors: list[list[float]], ntok: int) -> None:
        resp.write_json(
            {
                "object": "list",
                "data": [
                    {"object": "embedding", "embedding": v, "index": i}
                    for i, v in enumerate(vectors)
                ],
                "model": model,
                "usage": {"prompt_tokens": ntok, "total_tokens": ntok},
            }
        )

    # -- async smart-routed request ---------------------------------------

    def handle_llm_request(self, req: Request, resp: Response) -> None:
        try:
            body = req.json()
        except json.JSONDecodeError:
            resp.write_error("invalid JSON body", 400)
            return
        kind = str(body.get("kind") or "generate")
        prompt = str(body.get("prompt") or "")
        if not prompt and body.get("messages"):
            prompt = messages_to_prompt(body["messages"])
        quality = str(body.get("quality") or "")
        thinking = body.get("thinking")
        decision = self.router.route(
            kind=kind,
            model=str(body.get("model") or ""),
            prompt=prompt,
            provider=str(body.get("provider") or "auto"),
            quality=quality,
            thinking=bool(thinking) if thinking is not None else None,
            max_latency_ms=float(body.get("max_latency_ms") or 0),
            force_cloud=bool(body.get("force_cloud", False)),
        )
        payload = dict(body)
        payload.update(decision.payload_overlay())
        # the job carries the trace context so queue-wait / worker / rpc
        # spans from other threads and processes join this request's trace
        ctx = tracing.current_traceparent()
        if ctx and "_traceparent" not in payload:
            payload["_traceparent"] = ctx
        deadline = None
        if quality:
            deadline = time.time() + quality_deadline_s(quality)
        job = self.queue.submit(kind, payload, deadline_at=deadline)
        self.metrics.jobs_created.labels(kind=kind).inc()
        sp = tracing.current_span()
        if sp is not None:
            sp.set_attrs({"job_id": job.id, "quality": quality or ""})
        resp.write_json(
            {
                "job_id": job.id,
                "provider": decision.provider,
                "kind": kind,
                "model": decision.model,
                "device_id": decision.device_id,
                "reason": decision.reason,
            },
            status=202,
        )
