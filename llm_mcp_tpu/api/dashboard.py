"""Cluster dashboard, debug diagnostics, capacity, smoke test.

Parity map (reference `core/internal/api/handlers.go`):
  - GET /v1/dashboard single-JSON snapshot: 948-1092
  - Host→Node hierarchy builder: 1095-1264 (multi-port Ollama devices per
    host → here: multi-slice TPU devices per host via tags.base_device)
  - role inference: 1267-1292   issues[] generator: 1295-1339
  - GET /v1/debug/health deep health: 1372-1519
  - GET /v1/debug/actions catalog: 1522-1567
  - GET /v1/debug/capacity slots: 1570-1694 (slots = continuous-batch slots)
  - POST /v1/debug/test live smoke: 1697-1814
"""

from __future__ import annotations

import time
from typing import Any

from ..routing import Router
from ..state.catalog import Catalog
from ..state.db import Database
from ..state.queue import JobQueue
from ..telemetry import recorder as _flight
from ..utils.config import Config
from .http import Request, Response


class DashboardAPI:
    def __init__(
        self,
        *,
        db: Database,
        queue: JobQueue,
        catalog: Catalog,
        router: Router,
        cfg: Config,
        engines_info=None,  # callable -> dict with local engine stats
        route_stats=None,  # callable -> prefix-route outcome counters
        zoo_stats=None,  # callable -> ModelZoo.stats() | None (no zoo)
    ):
        self.db = db
        self.queue = queue
        self.catalog = catalog
        self.router = router
        self.cfg = cfg
        self.engines_info = engines_info or (lambda: {})
        self.route_stats = route_stats or (lambda: {})
        self.zoo_stats = zoo_stats or (lambda: None)
        self.started_at = time.time()

    # -- dashboard ---------------------------------------------------------

    def handle_dashboard(self, req: Request, resp: Response) -> None:
        counts = self.queue.counts_by_status()
        running = self.queue.list(status="running", limit=50)
        devices = self.catalog.list_devices()
        workers = self.catalog.workers_online()
        costs = self.catalog.costs_summary(since=time.time() - 86400)
        circuit = self.router.circuit.snapshot()
        hosts = self._host_tree(devices, circuit)
        engines = self.engines_info()
        issues = self._issues(counts, devices, workers, circuit, engines)
        # condensed self-speculative decoding view (full counters live under
        # engines[name]["speculation"]): is drafting paying off per engine?
        speculation = {
            name: {
                "enabled": bool(i["speculation"].get("enabled")),
                "accept_rate": round(i["speculation"].get("accept_rate", 0.0), 3),
                "tok_per_call": round(i["speculation"].get("tok_per_call", 0.0), 2),
                "verify_calls": int(i["speculation"].get("verify_calls", 0.0)),
            }
            for name, i in engines.items()
            if isinstance(i.get("speculation"), dict)
        }
        # condensed KV-pool view (full counters under engines[name]["memory"]):
        # how close is each engine to shedding, and how churned is the pool?
        memory = {
            name: {
                "headroom": round(i["memory"].get("headroom", 1.0), 3),
                "offered": int(i["memory"].get("offered", 0.0)),
                "preempted_held": int(i["memory"].get("preempted_held", 0.0)),
                "preempted": int(i["memory"].get("preempted_total", 0.0)),
                "restored": int(i["memory"].get("restored_total", 0.0)),
                "shed": int(i["memory"].get("shed_total", 0.0)),
            }
            for name, i in engines.items()
            if isinstance(i.get("memory"), dict)
        }
        # condensed paged-KV view (full stats under engines[name]["paging"]):
        # block occupancy, how much prefix sharing is multiplying capacity,
        # and the leak audit (anything nonzero is a refcount bug)
        paging = {
            name: {
                "blocks_used": int(i["paging"].get("blocks_used", 0.0)),
                "blocks_total": int(i["paging"].get("blocks_total", 0.0)),
                "sharing_ratio": round(i["paging"].get("sharing_ratio", 1.0), 3),
                "peak_sharing": round(
                    i["paging"].get("peak_sharing_ratio", 1.0), 3
                ),
                "cow_copies": int(i["paging"].get("cow_copies_total", 0.0)),
                "leaks": int(i["paging"].get("leaks", 0.0)),
            }
            for name, i in engines.items()
            if isinstance(i.get("paging"), dict)
        }
        # condensed migration view (full counters under
        # engines[name]["migration"]): snapshots moved each way and the
        # wire volume — only present while TPU_MIGRATE is on
        migration = {
            name: {
                "out": int(i["migration"].get("migrated_out_total", 0.0)),
                "in": int(i["migration"].get("migrated_in_total", 0.0)),
                "out_mb": round(
                    i["migration"].get("migrate_out_bytes_total", 0.0) / 2**20, 2
                ),
                "in_mb": round(
                    i["migration"].get("migrate_in_bytes_total", 0.0) / 2**20, 2
                ),
                "outbox": int(i["migration"].get("outbox_depth", 0.0)),
                "inbox": int(i["migration"].get("inbox_depth", 0.0)),
            }
            for name, i in engines.items()
            if isinstance(i.get("migration"), dict)
        }
        # prefill economy (scheduler stats, engines[name]["scheduler"]):
        # true vs padded prefill tokens through the dispatchers and the pad
        # waste the ragged path exists to erase — >20% waste on the
        # non-ragged path is the "turn on TPU_PREFILL_RAGGED" signal
        prefill = {
            name: {
                "true_tokens": int(i["scheduler"].get("prefill_true_tokens", 0.0)),
                "padded_tokens": int(
                    i["scheduler"].get("prefill_padded_tokens", 0.0)
                ),
                "pad_waste_pct": round(
                    i["scheduler"].get("prefill_pad_waste_pct", 0.0), 1
                ),
            }
            for name, i in engines.items()
            if isinstance(i.get("scheduler"), dict)
        }
        # condensed perf-observatory view (full document under
        # engines[name]["perf"] and /v1/debug/perf): token pacing (ITL),
        # the goodput split, and the live roofline for the active layout
        perf = {
            name: {
                "itl_p50_ms": round(
                    (i["perf"].get("itl") or {}).get("p50_ms", 0.0), 2
                ),
                "itl_p95_ms": round(
                    (i["perf"].get("itl") or {}).get("p95_ms", 0.0), 2
                ),
                "goodput_tok_per_s": round(
                    (i["perf"].get("goodput") or {}).get("goodput_tok_per_s", 0.0), 1
                ),
                "goodput_ratio": round(
                    (i["perf"].get("goodput") or {}).get("goodput_ratio", 1.0), 3
                ),
                "decode_mfu": (i["perf"].get("roofline") or {}).get("decode_mfu", 0.0),
                "decode_mbu": (i["perf"].get("roofline") or {}).get("decode_mbu", 0.0),
                "active_layout": (i["perf"].get("roofline") or {}).get(
                    "active_layout", ""
                ),
            }
            for name, i in engines.items()
            if isinstance(i.get("perf"), dict)
        }
        # condensed latency-waterfall + workload-capture view (full stats
        # under engines[name]["waterfall"]/["workload"], per-request rows
        # via /v1/debug/latency, the capture ring via /v1/debug/workload):
        # where did a finished request's wall actually go, and is the
        # traffic being captured for replay
        latency = {
            name: {
                "requests": int(i["waterfall"].get("requests", 0)),
                "coverage": i["waterfall"].get("coverage", 1.0),
                "total_p95_ms": i["waterfall"].get("total_p95_ms", 0.0),
                "p95_ms": {
                    stage: (i["waterfall"].get("stages") or {})
                    .get(stage, {})
                    .get("p95_ms", 0.0)
                    for stage in (
                        "admit_wait", "shed", "prefill_queue",
                        "prefill_compute", "decode", "stall", "preempt",
                    )
                },
                "captured": int(
                    (i.get("workload") or {}).get("records_total", 0)
                ),
            }
            for name, i in engines.items()
            if isinstance(i.get("waterfall"), dict)
        }
        # condensed flight-recorder view (full stats under
        # engines[name]["flight"], raw ring via /v1/debug/flight): anomaly
        # dump history per engine plus watchdog transition counts — the
        # "has anything weird happened" row of the dashboard
        anomalies = {
            name: {
                "dumps": int(
                    (i["flight"].get("anomaly") or {}).get("dumps_total", 0.0)
                ),
                "by_detector": (i["flight"].get("anomaly") or {}).get(
                    "by_detector"
                )
                or {},
                "last": (i["flight"].get("anomaly") or {}).get("last") or {},
                "watchdog": i["flight"].get("watchdog_transitions") or {},
                "dropped_events": int(i["flight"].get("dropped_events", 0.0)),
            }
            for name, i in engines.items()
            if isinstance(i.get("flight"), dict)
        }
        # condensed prefix-locality routing view (full tier stats under
        # engines[name]["prefix_tier"], knobs + digest via
        # /v1/debug/prefix): route outcomes plus each engine's chain
        # residency and wire traffic — is the fleet prefix tier hitting?
        rs = self.route_stats() or {}
        decided = rs.get("local", 0.0) + rs.get("fetch", 0.0) + rs.get("miss", 0.0)
        routing = {
            "outcomes": {
                k: int(rs.get(k, 0.0)) for k in ("local", "fetch", "miss", "fetch_fail")
            },
            "hit_rate": round(
                (rs.get("local", 0.0) + rs.get("fetch", 0.0)) / decided, 3
            )
            if decided
            else 0.0,
            "matched_tokens": int(rs.get("matched_tokens", 0.0)),
            "fetch_ms": round(rs.get("fetch_ms", 0.0), 1),
            "engines": {
                name: {
                    "chains": int(i["prefix_tier"].get("chains", 0.0)),
                    "longest_tokens": int(i["prefix_tier"].get("longest_tokens", 0.0)),
                    "exports": int(i["prefix_tier"].get("exports_total", 0.0)),
                    "imports": int(i["prefix_tier"].get("imports_total", 0.0)),
                    "import_rejects": int(
                        i["prefix_tier"].get("import_rejects_total", 0.0)
                    ),
                    "out_mb": round(
                        i["prefix_tier"].get("export_bytes_total", 0.0) / 2**20, 2
                    ),
                    "in_mb": round(
                        i["prefix_tier"].get("import_bytes_total", 0.0) / 2**20, 2
                    ),
                }
                for name, i in engines.items()
                if isinstance(i.get("prefix_tier"), dict)
            },
        }
        # condensed model-zoo + tenancy view (full residency document via
        # /v1/debug/zoo, per-tenant detail under engines[name]["perf"]
        # ["tenants"] and /v1/debug/perf): who is resident vs parked, the
        # swap churn, and each tenant's goodput split + 429s — the "is
        # tenant B still inside its SLO while A is hammered" row
        zs = self.zoo_stats()
        zoo = (
            {
                "resident": int(zs.get("resident", 0)),
                "parked": int(zs.get("parked", 0)),
                "hot": int(zs.get("hot", 0)),
                "swaps_in": int(zs.get("swaps_in_total", 0.0)),
                "swaps_out": int(zs.get("swaps_out_total", 0.0)),
                "hbm_resident_mb": round(
                    zs.get("hbm_resident_bytes", 0.0) / 2**20, 1
                ),
                "models": {
                    m: s.get("residency", "unknown")
                    for m, s in (zs.get("models") or {}).items()
                },
            }
            if isinstance(zs, dict)
            else {}
        )
        tenants = {
            name: {
                tenant: {
                    "goodput_ratio": round(t.get("goodput_ratio", 1.0), 3),
                    "goodput_tok_per_s": round(
                        t.get("goodput_tok_per_s", 0.0), 1
                    ),
                    "shed": int(t.get("shed", 0.0)),
                }
                for tenant, t in (i["perf"].get("tenants") or {}).items()
            }
            for name, i in engines.items()
            if isinstance(i.get("perf"), dict) and i["perf"].get("tenants")
        }
        # condensed compile-ledger view (full table via /v1/debug/compiles):
        # the ledger is process-wide — one block, costliest shapes first,
        # so cold-boot compile spend is visible without grepping logs
        led = _flight.get_compile_ledger()
        compiles = {"stats": led.stats(), "top": led.table()[:8]}
        resp.write_json(
            {
                "ts": time.time(),
                "uptime_s": round(time.time() - self.started_at, 1),
                "jobs": counts,
                "running_jobs": [j.to_dict() for j in running],
                "devices_online": sum(1 for d in devices if d["online"]),
                "devices_total": len(devices),
                "hosts": hosts,
                "workers_online": len(workers),
                "workers": workers,
                "costs_24h": costs,
                "circuit": circuit,
                "engines": engines,
                "speculation": speculation,
                "memory": memory,
                "paging": paging,
                "prefill": prefill,
                "perf": perf,
                "latency": latency,
                "migration": migration,
                "routing": routing,
                "anomalies": anomalies,
                "compiles": compiles,
                "zoo": zoo,
                "tenants": tenants,
                "issues": issues,
            }
        )

    def _host_tree(self, devices: list[dict], circuit: dict) -> list[dict]:
        """Group slice/port child devices under their base host
        (`handlers.go:1095-1264`). A TPU child device carries
        tags.base_device, like the reference's per-port Ollama children."""
        hosts: dict[str, dict] = {}
        for d in devices:
            tags = d.get("tags") or {}
            base = str(tags.get("base_device") or d["id"])
            host = hosts.setdefault(
                base, {"host": base, "online": False, "nodes": [], "role": ""}
            )
            node = {
                "id": d["id"],
                "name": d["name"],
                "addr": d["addr"],
                "online": bool(d["online"]),
                "last_seen": d["last_seen"],
                "models": self.catalog.device_models(d["id"]),
                "circuit": circuit.get(d["id"], {}).get("status", "ok"),
                "tags": tags,
            }
            host["nodes"].append(node)
            host["online"] = host["online"] or node["online"]
        for h in hosts.values():
            h["role"] = self._infer_role(h)
        return sorted(hosts.values(), key=lambda h: h["host"])

    @staticmethod
    def _infer_role(host: dict) -> str:
        """Role inference (`handlers.go:1267-1292`), TPU flavored."""
        tags_union: dict[str, Any] = {}
        models: list[str] = []
        for n in host["nodes"]:
            tags_union.update(n.get("tags") or {})
            models += n.get("models") or []
        if tags_union.get("tpu") or tags_union.get("chips"):
            return "tpu-executor"
        if tags_union.get("cloud"):
            return "cloud-gateway"
        if any("embed" in m for m in models):
            return "embedder"
        if models:
            return "inference"
        return "node"

    def _issues(self, counts, devices, workers, circuit, engines=None) -> list[str]:
        """Plain-language cluster problems (`handlers.go:1295-1339`)."""
        issues: list[str] = []
        online = [d for d in devices if d["online"]]
        if not online:
            issues.append("No devices online — nothing can serve inference.")
        if not workers:
            issues.append("No workers have heartbeated in 90s — async jobs will not run.")
        queued = counts.get("queued", 0)
        if queued > 50:
            issues.append(f"{queued} jobs queued — queue may be stuck or underprovisioned.")
        errors = counts.get("error", 0)
        if errors > 10:
            issues.append(f"{errors} jobs in error state.")
        degraded = [d for d, st in circuit.items() if st.get("status") == "degraded"]
        if degraded:
            issues.append(f"Devices degraded by circuit breaker: {', '.join(sorted(degraded))}.")
        stale = [
            d["id"]
            for d in online
            if d["last_seen"] and time.time() - d["last_seen"] > 600
        ]
        if stale:
            issues.append(f"Online devices not seen for >10min: {', '.join(sorted(stale))}.")
        eng = engines if engines is not None else self.engines_info()
        stalled = [name for name, info in eng.items() if info.get("stalled")]
        if stalled:
            issues.append(
                "Local engine(s) STALLED — accelerator link unresponsive, "
                f"requests failing over: {', '.join(sorted(stalled))}."
            )
        dropped = sum(
            int(i["flight"].get("dropped_events", 0.0))
            for i in eng.values()
            if isinstance(i.get("flight"), dict)
        )
        if dropped:
            issues.append(
                f"Flight recorder dropped {dropped} events during dump "
                "freezes — raise TPU_FLIGHT_RING or TPU_FLIGHT_DUMP_INTERVAL_S."
            )
        recent = [
            (name, i["flight"]["anomaly"]["last"])
            for name, i in eng.items()
            if isinstance(i.get("flight"), dict)
            and (i["flight"].get("anomaly") or {}).get("last")
        ]
        for name, last in recent:
            if time.time() - float(last.get("ts", 0.0)) < 900:
                issues.append(
                    f"Engine {name} anomaly in the last 15min: "
                    f"{last.get('detector', '?')} — {last.get('reason', '')} "
                    f"(journal: {last.get('journal') or 'n/a'})."
                )
        return issues

    # -- debug -------------------------------------------------------------

    def handle_health(self, req: Request, resp: Response) -> None:
        t0 = time.time()
        db_ok, db_err = True, ""
        try:
            self.db.query_one("SELECT 1 AS ok")
        except Exception as e:
            db_ok, db_err = False, str(e)
        db_ms = (time.time() - t0) * 1000
        devices = self.catalog.list_devices(online_only=True)
        checks = {
            "db": {"ok": db_ok, "latency_ms": round(db_ms, 2), "error": db_err},
            "devices_online": len(devices),
            "workers_online": len(self.catalog.workers_online()),
            "engines": self.engines_info(),
        }
        status = "ok" if db_ok else "error"
        resp.write_json({"status": status, "checks": checks}, 200 if db_ok else 503)

    def handle_actions(self, req: Request, resp: Response) -> None:
        """Action catalog (`handlers.go:1522-1567`)."""
        resp.write_json(
            {
                "actions": [
                    {"method": "POST", "path": "/v1/discovery/run", "desc": "trigger device discovery"},
                    {"method": "POST", "path": "/v1/debug/test", "desc": "run live smoke test"},
                    {"method": "POST", "path": "/v1/jobs", "desc": "submit a job"},
                    {"method": "POST", "path": "/v1/llm/request", "desc": "smart-routed LLM request"},
                    {"method": "POST", "path": "/v1/chat/completions", "desc": "OpenAI-compatible chat"},
                    {"method": "POST", "path": "/v1/embeddings", "desc": "OpenAI-compatible embeddings"},
                    {"method": "GET", "path": "/v1/dashboard", "desc": "cluster snapshot"},
                    {"method": "GET", "path": "/v1/debug/capacity", "desc": "slot capacity"},
                    {"method": "POST", "path": "/v1/models/sync", "desc": "sync model catalog"},
                ]
            }
        )

    def handle_capacity(self, req: Request, resp: Response) -> None:
        """Slots = engine batch slots for TPU devices (the reference's
        nodes × DEVICE_MAX_CONCURRENCY, `handlers.go:1570-1694`; here the
        per-device continuous-batch slot count from tags)."""
        devices = self.catalog.list_devices(online_only=True)
        total_slots = 0
        per_device = []
        running_by_dev = {
            r["device_id"]: r["n"]
            for r in self.db.query(
                "SELECT device_id, COUNT(*) AS n FROM jobs WHERE status='running'"
                " AND device_id IS NOT NULL GROUP BY device_id"
            )
        }
        for d in devices:
            tags = d.get("tags") or {}
            slots = int(tags.get("slots", 0) or 0) or self.cfg.device_max_concurrency
            used = running_by_dev.get(d["id"], 0)
            total_slots += slots
            per_device.append(
                {"device_id": d["id"], "slots": slots, "running": used, "free": max(slots - used, 0)}
            )
        resp.write_json(
            {
                "total_slots": total_slots,
                "running": sum(p["running"] for p in per_device),
                "devices": per_device,
            }
        )

    def handle_smoke_test(self, req: Request, resp: Response) -> None:
        """Live smoke (`handlers.go:1697-1814`): db ping/read, per-device
        reachability, queue round-trip with cleanup."""
        results: dict[str, Any] = {}
        t0 = time.time()
        try:
            self.db.query_one("SELECT 1 AS ok")
            results["db_ping"] = {"ok": True, "ms": round((time.time() - t0) * 1000, 2)}
        except Exception as e:
            results["db_ping"] = {"ok": False, "error": str(e)}
        try:
            results["db_read"] = {
                "ok": True,
                "jobs": self.queue.counts_by_status(),
                "devices": len(self.catalog.list_devices()),
            }
        except Exception as e:
            results["db_read"] = {"ok": False, "error": str(e)}
        # queue round-trip with a unique kind so a real user's queued job can
        # never be claimed by the smoke test; leftovers are canceled
        try:
            import uuid

            kind = f"smoke.{uuid.uuid4().hex[:8]}"
            job = self.queue.submit(kind, {"payload": "smoke"})
            claimed = self.queue.claim("smoke-test", kinds=[kind])
            ok = claimed is not None and claimed.id == job.id
            if ok:
                self.queue.complete(job.id, "smoke-test", result={"echo": "smoke"})
            final = self.queue.get(job.id)
            if final is not None and final.status not in ("done",):
                self.queue.cancel(job.id)
            results["queue_roundtrip"] = {
                "ok": bool(ok and final and final.status == "done"),
                "job_id": job.id,
            }
        except Exception as e:
            results["queue_roundtrip"] = {"ok": False, "error": str(e)}
        all_ok = all(v.get("ok") for v in results.values())
        resp.write_json({"status": "ok" if all_ok else "failed", "results": results})
