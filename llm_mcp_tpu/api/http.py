"""Zero-framework threaded HTTP layer: route table, JSON/SSE helpers.

The reference's API is a plain Go `http.ServeMux` with hand-rolled helpers
(`core/internal/api/helpers.go:11-43`) and its MCP bridge is zero-framework
`node:http` (`mcp/src/index.ts`). Same spirit here: stdlib
ThreadingHTTPServer, one thread per connection — which is exactly what
blocking-queue token streams from the engine want (no async bridging).
"""

from __future__ import annotations

import json
import logging
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

from ..telemetry import tracing
from ..utils.faults import maybe_fail

log = logging.getLogger("api")

MAX_BODY = 10 * 1024 * 1024  # 10MB cap, as the reference's chat handler


class Request:
    def __init__(self, handler: "_Handler", params: dict[str, str]):
        self._h = handler
        self.method = handler.command
        parsed = urlparse(handler.path)
        self.path = parsed.path
        self.query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        self.params = params  # path parameters, e.g. {id}
        self.headers = handler.headers
        self._body: bytes | None = None
        self.consumed = 0  # bytes of the body actually read

    def body(self) -> bytes:
        if self._body is None:
            length = int(self.headers.get("Content-Length") or 0)
            self._body = self._h.rfile.read(min(length, MAX_BODY)) if length else b""
            self.consumed = len(self._body)
        return self._body

    def json(self) -> Any:
        raw = self.body()
        if not raw:
            return {}
        return json.loads(raw)


class Response:
    """Write-side helper bound to one connection."""

    def __init__(self, handler: "_Handler"):
        self._h = handler
        self.started = False
        self.status = 0  # last status written (0 = nothing sent yet)
        # extra response headers (e.g. X-Selected-Model) emitted by every
        # write_* / start_sse below
        self.extra_headers: dict[str, str] = {}

    def _send_extra(self) -> None:
        for k, v in self.extra_headers.items():
            self._h.send_header(k, v)

    def write_json(self, obj: Any, status: int = 200) -> None:
        data = json.dumps(obj).encode("utf-8")
        h = self._h
        h.send_response(status)
        h.send_header("Content-Type", "application/json")
        h.send_header("Content-Length", str(len(data)))
        self._send_extra()
        h.end_headers()
        h.wfile.write(data)
        self.started = True
        self.status = status

    def write_error(self, message: str, status: int = 400, code: str = "") -> None:
        # error contract shape mirrors the reference (helpers_test.go:14-127)
        self.write_json({"error": {"message": message, "code": code or str(status)}}, status)

    def write_bytes(self, data: bytes, content_type: str, status: int = 200) -> None:
        h = self._h
        h.send_response(status)
        h.send_header("Content-Type", content_type)
        h.send_header("Content-Length", str(len(data)))
        self._send_extra()
        h.end_headers()
        h.wfile.write(data)
        self.started = True
        self.status = status

    # -- SSE ---------------------------------------------------------------

    def start_sse(self) -> None:
        h = self._h
        # No Content-Length: the stream ends when the server closes the
        # connection, so keep-alive must be off for this connection.
        h.close_connection = True
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.send_header("X-Accel-Buffering", "no")
        self._send_extra()
        h.end_headers()
        self.started = True
        self.status = 200

    def sse_data(self, payload: Any) -> bool:
        """Send one `data:` frame; JSON-encodes non-strings. Returns False
        when the client disconnected."""
        if isinstance(payload, str):
            data = payload
        else:
            data = json.dumps(payload)
        try:
            self._h.wfile.write(f"data: {data}\n\n".encode("utf-8"))
            self._h.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False

    def sse_event(self, event: str, payload: Any) -> bool:
        data = payload if isinstance(payload, str) else json.dumps(payload)
        try:
            self._h.wfile.write(f"event: {event}\ndata: {data}\n\n".encode("utf-8"))
            self._h.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False


HandlerFn = Callable[[Request, Response], None]


class _Route:
    def __init__(self, method: str, pattern: str, fn: HandlerFn):
        self.method = method
        self.fn = fn
        # "/v1/jobs/{id}/stream" → regex with named groups
        regex = re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern)
        self.re = re.compile(f"^{regex}$")


class HTTPApi:
    def __init__(self):
        self._routes: list[_Route] = []
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def route(self, method: str, pattern: str, fn: HandlerFn) -> None:
        self._routes.append(_Route(method.upper(), pattern, fn))

    @staticmethod
    def _drain(handler: "_Handler", consumed: int) -> None:
        """Consume any unread request body so the next request on a
        keep-alive connection doesn't parse leftover bytes as its request
        line. Oversized bodies are not read — the connection closes."""
        try:
            length = int(handler.headers.get("Content-Length") or 0)
        except (TypeError, ValueError):
            length = 0
        remaining = length - consumed
        if remaining <= 0:
            return
        if remaining > MAX_BODY:
            handler.close_connection = True
            return
        try:
            handler.rfile.read(remaining)
        except OSError:
            handler.close_connection = True

    def dispatch(self, handler: "_Handler") -> None:
        path = urlparse(handler.path).path
        method = handler.command
        path_matched = False
        for r in self._routes:
            m = r.re.match(path)
            if not m:
                continue
            path_matched = True
            if r.method != method:
                continue
            req = Request(handler, m.groupdict())
            resp = Response(handler)
            tracer = tracing.get_tracer()
            # Root span per request, joining an inbound W3C traceparent when
            # present. Probe endpoints are untraced: /health and /metrics
            # polling would evict every interesting trace from the ring.
            trace = tracer.enabled and path not in tracing.UNTRACED_PATHS
            span = (
                tracer.start_span(
                    f"http {method} {path}",
                    parent=req.headers.get("traceparent") or tracing.NEW_TRACE,
                    attrs={"http.method": method, "http.path": path},
                )
                if trace
                else None
            )
            if span is not None:
                resp.extra_headers["X-Trace-Id"] = span.trace_id
                tracing.push_span(span)
            try:
                maybe_fail("api.request", path)
                r.fn(req, resp)
            except json.JSONDecodeError:
                if not resp.started:
                    resp.write_error("invalid JSON body", 400)
            except (BrokenPipeError, ConnectionResetError):
                handler.close_connection = True
                if span is not None:
                    span.set_error("client disconnected")
            except Exception as e:  # noqa: BLE001 — handler crash → 500
                log.exception("handler error %s %s", method, path)
                if span is not None:
                    span.set_error(f"{type(e).__name__}: {e}")
                if not resp.started:
                    resp.write_error(f"internal error: {e}", 500)
            finally:
                if span is not None:
                    tracing.pop_span(span)
                    if resp.status:
                        span.set_attr("http.status", resp.status)
                    span.end()
                self._drain(handler, req.consumed)
            return
        self._drain(handler, 0)
        resp = Response(handler)
        if path_matched:
            resp.write_error("method not allowed", 405)
        else:
            resp.write_error("not found", 404)

    # -- lifecycle -----------------------------------------------------------

    def serve(self, host: str, port: int) -> ThreadingHTTPServer:
        api = self

        class _Bound(_Handler):
            _api = api

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            # default backlog (5) drops/resets connections when many SSE
            # clients reconnect at once (64+ concurrent streams re-issuing
            # requests hit this in the serving benchmark)
            request_queue_size = 256

        self._server = _Server((host, port), _Bound)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="http-api", daemon=True
        )
        self._thread.start()
        return self._server

    @property
    def port(self) -> int:
        return self._server.server_address[1] if self._server else 0

    def shutdown(self) -> None:
        if self._server:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class _Handler(BaseHTTPRequestHandler):
    _api: HTTPApi
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt: str, *args: Any) -> None:
        log.debug("%s %s", self.address_string(), fmt % args)

    def _handle(self) -> None:
        self._api.dispatch(self)

    do_GET = _handle
    do_POST = _handle
    do_PUT = _handle
    do_DELETE = _handle
    do_PATCH = _handle
