"""`python -m llm_mcp_tpu.api` — boot the core server with local engines.

The process-level analog of the reference's `core/cmd/core/main.go`:
construct state, policy, API; load the configured models into TPU engines;
serve until SIGTERM.
"""

from __future__ import annotations

import logging
import os
import signal
import sys


def main() -> None:
    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO"),
        format='{"ts":"%(asctime)s","level":"%(levelname)s","logger":"%(name)s","msg":"%(message)s"}',
    )
    log = logging.getLogger("main")

    from ..utils.config import Config, enable_compile_cache

    cfg = Config()
    enable_compile_cache()

    if os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip().lower() == "cpu":
        # an already-registered accelerator plugin ignores the env var; the
        # config-level pin is the one mechanism it respects (CI / CPU sims)
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from ..executor import EmbeddingEngine, GenerationEngine
    from .server import CoreServer

    gen_engines = {}
    embed_engines = {}
    if os.environ.get("TPU_DISABLE_ENGINES", "") not in ("1", "true"):
        # multi-host first (must precede the first jax op), then the mesh:
        # TPU_MESH_SHAPE="dp=1,tp=8" shards the engines over it; empty = one
        # chip. make_global_mesh lays dp/pp over DCN on multi-slice fleets.
        from ..parallel import distributed

        mesh = None
        if cfg.tpu_mesh_shape:
            multi = distributed.initialize()
            mesh = distributed.make_global_mesh(cfg.tpu_mesh_shape)
            log.info("serving over mesh %s", dict(zip(mesh.axis_names, mesh.devices.shape)))
            if multi and cfg.tpu_slice_cmd_addr:
                # Multi-PROCESS serving: the model spans hosts, so the whole
                # cluster serves as ONE schedulable device — process 0 runs
                # the leader SliceEngine inside CoreServer (registers via
                # discovery exactly like a single-host engine); every other
                # process mirrors dispatches over the command channel and
                # never binds HTTP (executor/engine.py SliceEngine).
                import jax

                from ..executor import SliceEngine

                eng = SliceEngine(
                    cfg.tpu_model,
                    mesh=mesh,
                    cmd_addr=cfg.tpu_slice_cmd_addr,
                    max_slots=cfg.tpu_max_slots,
                    max_seq_len=cfg.tpu_max_seq_len,
                    dtype=jnp.bfloat16,
                    quant=cfg.tpu_quant,
                    weights_dir=cfg.tpu_weights_dir,
                    prefill_chunk=cfg.tpu_prefill_chunk,
                    target_ttft_ms=cfg.tpu_target_ttft_ms,
                )
                if jax.process_index() != 0:
                    log.info("slice follower %d/%d: mirroring dispatches",
                             jax.process_index(), jax.process_count())
                    eng.run_follower()
                    return
                gen_engines[cfg.tpu_model] = eng.start()
        model = cfg.tpu_model
        if model in gen_engines:
            log.info("generation engine: %s (multi-host slice leader)", model)
        else:
            log.info("loading generation engine: %s", model)
            gen_engines[model] = GenerationEngine(
                model,
                mesh=mesh,
                max_slots=cfg.tpu_max_slots,
                max_seq_len=cfg.tpu_max_seq_len,
                dtype=jnp.bfloat16,
                weights_dir=cfg.tpu_weights_dir,
                quant=cfg.tpu_quant,
                kv_quant=cfg.tpu_kv_quant,
                prefill_chunk=cfg.tpu_prefill_chunk,
                decode_compact=cfg.tpu_decode_compact,
                prompt_cache_mb=cfg.tpu_prompt_cache_mb,
                prefill_buckets=cfg.tpu_prefill_buckets,
                target_ttft_ms=cfg.tpu_target_ttft_ms,
            ).start()
        emodel = cfg.tpu_embed_model
        cfg.warn_embed_dir_gap(log)
        log.info("loading embedding engine: %s", emodel)
        embed_engines[emodel] = EmbeddingEngine(
            emodel,
            max_seq_len=min(cfg.tpu_max_seq_len, 8192),
            dtype=jnp.bfloat16,
            weights_dir=cfg.tpu_embed_weights_dir,
            quant=cfg.tpu_embed_quant,
        )

    zoo = None
    if gen_engines and cfg.tpu_zoo_models:
        # Model zoo (executor/zoo.py): TPU_ZOO_MODELS co-hosts extra models
        # on this chip. The factory owns every construction kwarg, so a
        # swap-in builds engines identical to the primary one; host_params
        # is None on the cold first load, a parked host tree afterwards.
        from ..executor import ModelZoo

        def _zoo_factory(name, host_params, _mesh=mesh):
            return GenerationEngine(
                name,
                mesh=_mesh,
                params=host_params,
                max_slots=cfg.tpu_max_slots,
                max_seq_len=cfg.tpu_max_seq_len,
                dtype=jnp.bfloat16,
                weights_dir=cfg.tpu_weights_dir,
                quant=cfg.tpu_quant,
                kv_quant=cfg.tpu_kv_quant,
                prefill_chunk=cfg.tpu_prefill_chunk,
                decode_compact=cfg.tpu_decode_compact,
                prompt_cache_mb=cfg.tpu_prompt_cache_mb,
                prefill_buckets=cfg.tpu_prefill_buckets,
                target_ttft_ms=cfg.tpu_target_ttft_ms,
            )

        zoo = ModelZoo(_zoo_factory, hot=cfg.tpu_zoo_hot, swap=cfg.tpu_zoo_swap)
        catalog = [
            m.strip() for m in cfg.tpu_zoo_models.split(",")
            if m.strip() and m.strip() not in gen_engines
        ]
        for i, name in enumerate(catalog):
            # the first TPU_ZOO_HOT catalog models load at boot (the hot
            # set); the tail parks until a request pays the swap-in
            zoo.register(name, resident=i < cfg.tpu_zoo_hot)
        log.info(
            "model zoo: %d models (%s resident), hot=%d swap=%s",
            len(catalog), ",".join(zoo.resident_models()) or "none",
            cfg.tpu_zoo_hot, cfg.tpu_zoo_swap,
        )

    host, _, port = cfg.http_addr.rpartition(":")
    server = CoreServer(
        cfg, gen_engines=gen_engines, embed_engines=embed_engines, zoo=zoo
    ).start(host or "0.0.0.0", int(port or 8080))

    grpc_server = None
    if cfg.grpc_addr:
        from ..rpc import GrpcCoreServer

        ghost, _, gport = cfg.grpc_addr.rpartition(":")
        grpc_server = GrpcCoreServer(
            server.queue,
            server.catalog,
            circuit=server.router.circuit,
            device_max_concurrency=cfg.device_max_concurrency,
            default_lease_s=float(cfg.worker_lease_seconds),
        )
        if gen_engines:
            # KV transfer endpoint on the same server: remote migration in,
            # and the fleet prefix tier's PrefixFetch out (handlers must be
            # registered before start). Advertise the dialable address so
            # peers' routers can pull prefixes from this process.
            eng = next(iter(gen_engines.values()))
            grpc_server.enable_kv_transfer(
                eng.migrate_import_stream,
                prefix_export=server.prefix_export,
                prefix_export_hash=server.prefix_export_hash,
            )
            server.transfer_addr = server.transfer_addr or cfg.grpc_addr
        grpc_server.start(f"{ghost or '0.0.0.0'}:{gport or 9090}")
        log.info("grpc worker protocol on %s", cfg.grpc_addr)

    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    try:
        while not stop:
            signal.pause()
    finally:
        log.info("shutting down")
        if grpc_server is not None:
            grpc_server.stop()
        server.shutdown()


if __name__ == "__main__":
    main()
