"""Job queue HTTP surface: submit/get/list/claim/complete/fail/heartbeat +
SSE status streaming + worker registration + device offline reports.

Parity map (reference):
  - submit:    `core/internal/api/handlers.go:35-94`
  - get/list:  `handlers.go:96-199`
  - claim:     `handlers.go:200-293` (per-device concurrency cap)
  - complete:  `handlers.go:295-347`   fail: `349-411`   heartbeat: `413-445`
  - SSE job stream via LISTEN + 15s safety re-poll: `handlers.go:481-608`
  - worker register: `grpcserver/server.go:98-124`
  - devices offline → lease reset: `offline_handler.go:12-38`,
    worker side-channel `worker/llm_worker/main.py:180-186`
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any

from ..routing import Router
from ..state.catalog import Catalog
from ..state.jobtrace import record_job_end, record_queue_wait
from ..state.queue import JobQueue, JobStatus
from ..telemetry import Metrics, tracing
from ..utils.config import Config
from .http import Request, Response

log = logging.getLogger("jobs")

SSE_REPOLL_S = 15.0  # safety re-poll interval, as the reference
SSE_MAX_S = 600.0


class JobsAPI:
    def __init__(
        self,
        *,
        queue: JobQueue,
        catalog: Catalog,
        router: Router,
        metrics: Metrics,
        cfg: Config,
        overload_check=None,
    ):
        self.queue = queue
        self.catalog = catalog
        self.router = router
        self.metrics = metrics
        self.cfg = cfg
        # () -> (shed: bool, retry_after_s: float) — wired by CoreServer to
        # the local engine's KV-pool admission state. Above the watermark,
        # claims defer instead of leasing work the executor cannot run
        # (the lease would just expire and bounce the job's attempt count).
        self.overload_check = overload_check

    # -- submit / read -----------------------------------------------------

    def handle_submit(self, req: Request, resp: Response) -> None:
        body = req.json()
        kind = str(body.get("kind") or "")
        if not kind:
            resp.write_error("kind required", 400)
            return
        payload = body.get("payload") or {}
        if not isinstance(payload, dict):
            resp.write_error("payload must be an object", 400)
            return
        # device-limit gate at submit (`handlers.go:70-78`)
        device_id = str(payload.get("device_id") or "")
        model = str(payload.get("model") or "")
        if device_id and model and self.router.limits is not None:
            ok, why = self.router.limits.model_allowed(device_id, model)
            if not ok:
                resp.write_error(f"model not allowed on device: {why}", 422)
                return
        try:
            priority = int(body.get("priority") or 0)
            max_attempts = int(body.get("max_attempts") or 0) or None
            deadline_at = float(body["deadline_at"]) if body.get("deadline_at") else None
        except (TypeError, ValueError):
            resp.write_error("priority/max_attempts/deadline_at must be numeric", 400)
            return
        # stamp the submitting request's trace context into the payload so
        # claim/complete (possibly another process) can join spans to it
        ctx = tracing.current_traceparent()
        if ctx and "_traceparent" not in payload:
            payload["_traceparent"] = ctx
        job = self.queue.submit(
            kind,
            payload,
            priority=priority,
            max_attempts=max_attempts,
            deadline_at=deadline_at,
        )
        self.metrics.jobs_created.labels(kind=kind).inc()
        sp = tracing.current_span()
        if sp is not None:
            sp.set_attrs({"job_id": job.id, "kind": kind})
        resp.write_json({"job_id": job.id, "status": job.status}, status=202)

    def handle_get(self, req: Request, resp: Response) -> None:
        job = self.queue.get(req.params["id"])
        if job is None:
            resp.write_error("job not found", 404)
            return
        resp.write_json(job.to_dict())

    def handle_list(self, req: Request, resp: Response) -> None:
        jobs = self.queue.list(
            status=req.query.get("status"),
            kind=req.query.get("kind"),
            limit=int(req.query.get("limit") or 100),
            offset=int(req.query.get("offset") or 0),
        )
        resp.write_json({"jobs": [j.to_dict() for j in jobs]})

    def handle_cancel(self, req: Request, resp: Response) -> None:
        if not self.queue.cancel(req.params["id"]):
            resp.write_error("job not cancelable", 409)
            return
        resp.write_json({"status": "canceled"})

    # -- worker protocol ---------------------------------------------------

    def handle_claim(self, req: Request, resp: Response) -> None:
        body = req.json()
        worker_id = str(body.get("worker_id") or "")
        if not worker_id:
            resp.write_error("worker_id required", 400)
            return
        kinds = body.get("kinds") or []
        if self.overload_check is not None:
            shed, retry_after = self.overload_check()
            if shed:
                # no lease: tell the worker when capacity should exist
                self.catalog.worker_heartbeat(worker_id)
                resp.write_json(
                    {
                        "job": None,
                        "deferred": True,
                        "retry_after": max(1, int(retry_after + 0.5)),
                    },
                    status=200,
                )
                return
        job = self.queue.claim(
            worker_id,
            kinds=[str(k) for k in kinds],
            lease_seconds=float(body.get("lease_seconds") or self.cfg.worker_lease_seconds),
            device_max_concurrency=self.cfg.device_max_concurrency,
        )
        self.catalog.worker_heartbeat(worker_id)
        if job is None:
            resp.write_json({"job": None}, status=200)
            return
        record_queue_wait(job, worker_id=worker_id)
        resp.write_json({"job": job.to_dict()})

    def handle_complete(self, req: Request, resp: Response) -> None:
        body = req.json()
        job_id = req.params["id"]
        worker_id = str(body.get("worker_id") or "")
        ok = self.queue.complete(
            job_id, worker_id, result=body.get("result"), metrics=body.get("metrics")
        )
        if not ok:
            resp.write_error("job not running under this worker", 409)
            return
        job = self.queue.get(job_id)
        if job is not None:
            dev = job.payload.get("device_id") or job.device_id
            if dev:
                self.router.circuit.record(dev, ok=True)
            self._record_benchmark_result(job)
            record_job_end(job, JobStatus.DONE)
        resp.write_json({"status": "done"})

    def handle_fail(self, req: Request, resp: Response) -> None:
        body = req.json()
        worker_id = str(body.get("worker_id") or "")
        error = str(body.get("error") or "unknown error")
        status = self.queue.fail(req.params["id"], worker_id, error)
        if status is None:
            resp.write_error("job not running under this worker", 409)
            return
        job = self.queue.get(req.params["id"])
        if job is not None:
            dev = job.payload.get("device_id") or job.device_id
            if dev:
                self.router.circuit.record(dev, ok=False)
            if status in JobStatus.TERMINAL:  # retries keep the trace open
                record_job_end(job, status)
        resp.write_json({"status": status})

    def handle_heartbeat(self, req: Request, resp: Response) -> None:
        body = req.json()
        worker_id = str(body.get("worker_id") or "")
        ok = self.queue.heartbeat(
            req.params["id"],
            worker_id,
            lease_seconds=float(body.get("lease_seconds") or self.cfg.worker_lease_seconds),
        )
        self.catalog.worker_heartbeat(worker_id)
        if not ok:
            resp.write_error("job not running under this worker", 409)
            return
        resp.write_json({"status": "ok"})

    def handle_worker_register(self, req: Request, resp: Response) -> None:
        body = req.json()
        worker_id = str(body.get("worker_id") or "")
        if not worker_id:
            resp.write_error("worker_id required", 400)
            return
        self.catalog.register_worker(
            worker_id,
            name=str(body.get("name") or ""),
            kinds=[str(k) for k in body.get("kinds") or []],
        )
        resp.write_json({"status": "registered", "worker_id": worker_id})

    def handle_devices_offline(self, req: Request, resp: Response) -> None:
        body = req.json()
        ids = body.get("device_ids") or ([body["device_id"]] if body.get("device_id") else [])
        ids = [str(i) for i in ids if i]
        if not ids:
            resp.write_error("device_ids required", 400)
            return
        for dev in ids:
            self.catalog.set_device_online(dev, False)
            self.router.circuit.record(dev, ok=False)
        requeued = self.queue.requeue_device_jobs(ids)
        resp.write_json({"status": "ok", "requeued_jobs": requeued})

    # -- SSE job stream ----------------------------------------------------

    def handle_stream(self, req: Request, resp: Response) -> None:
        """Push job status changes over SSE: initial snapshot, then an event
        per transition (notify-driven with a safety re-poll), ending at a
        terminal status. The reference's LISTEN-based stream
        (`handlers.go:481-608`) with the in-process notify bus."""
        job_id = req.params["id"]
        # version read BEFORE job state: an update racing the read makes the
        # next wait return immediately (no lost wakeup / re-poll stall)
        version = self.queue.update_version
        job = self.queue.get(job_id)
        if job is None:
            resp.write_error("job not found", 404)
            return
        resp.start_sse()
        if not resp.sse_event("status", job.to_dict()):
            return
        last_status = job.status
        last_updated = job.updated_at
        deadline = time.time() + SSE_MAX_S
        while job.status not in JobStatus.TERMINAL and time.time() < deadline:
            version = self.queue.wait_for_update(SSE_REPOLL_S, since=version)
            job = self.queue.get(job_id)
            if job is None:
                break
            if job.status != last_status or job.updated_at != last_updated:
                last_status, last_updated = job.status, job.updated_at
                if not resp.sse_event("status", job.to_dict()):
                    return
        resp.sse_event("end", {"id": job_id, "status": last_status})

    # -- benchmark results -------------------------------------------------

    def _record_benchmark_result(self, job) -> None:
        from ..state.catalog import record_benchmark_from_job

        record_benchmark_from_job(self.catalog, job)
