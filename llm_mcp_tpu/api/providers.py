"""Cloud fallback providers: OpenRouter / OpenAI HTTP clients.

Parity: the reference keeps cloud chat/embeddings as HTTP fallbacks
(`worker/llm_worker/main.py:274-327`, sync proxy `handlers.go:2235-2305`)
— same role here. The TPU executor is the primary provider; these engage on
`force_cloud`, cloud-namespaced model ids ("vendor/model"), or when smart
routing falls back (router.py `_find_cloud_model`).

Also: live OpenRouter balance query (`handlers.go:2688-2776`) and model
catalog sync by category (`handlers.go:3176-3287`).
"""

from __future__ import annotations

import json
import logging
from typing import Any, Iterator

import httpx

from ..utils.config import Config

log = logging.getLogger("providers")

CLOUD_TIMEOUT_S = 120.0


class CloudClient:
    """Thin OpenAI-wire client for OpenRouter (preferred) or OpenAI."""

    def __init__(self, cfg: Config):
        self.cfg = cfg

    def _base(self) -> tuple[str, str]:
        if self.cfg.has_openrouter():
            return self.cfg.openrouter_base_url.rstrip("/"), self.cfg.openrouter_api_key
        if self.cfg.has_openai():
            return self.cfg.openai_base_url.rstrip("/"), self.cfg.openai_api_key
        raise RuntimeError("no cloud provider configured")

    def _headers(self, key: str) -> dict[str, str]:
        return {"Authorization": f"Bearer {key}", "Content-Type": "application/json"}

    def chat(self, body: dict[str, Any]) -> dict[str, Any]:
        base, key = self._base()
        body = dict(body)
        body.pop("stream", None)
        r = httpx.post(
            f"{base}/chat/completions", json=body, headers=self._headers(key),
            timeout=CLOUD_TIMEOUT_S,
        )
        r.raise_for_status()
        return r.json()

    def chat_stream(self, body: dict[str, Any]) -> Iterator[Any]:
        """Yield SSE payloads (str or dict). Usage is extracted from the
        final chunk as in the reference (`handlers.go:2235-2305`)."""
        base, key = self._base()
        body = dict(body)
        body["stream"] = True
        with httpx.stream(
            "POST", f"{base}/chat/completions", json=body,
            headers=self._headers(key), timeout=CLOUD_TIMEOUT_S,
        ) as r:
            r.raise_for_status()
            for line in r.iter_lines():
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data.strip() == "[DONE]":
                    return
                try:
                    yield json.loads(data)
                except json.JSONDecodeError:
                    yield data

    def embed(self, model: str, texts: list[str], dimensions: int | None) -> dict[str, Any]:
        base, key = self._base()
        body: dict[str, Any] = {"model": model, "input": texts}
        if dimensions:
            body["dimensions"] = dimensions
        r = httpx.post(
            f"{base}/embeddings", json=body, headers=self._headers(key),
            timeout=CLOUD_TIMEOUT_S,
        )
        r.raise_for_status()
        return r.json()

    def balance(self) -> dict[str, Any]:
        """Live OpenRouter key/balance query (`handlers.go:2688-2776`)."""
        if not self.cfg.has_openrouter():
            raise RuntimeError("OpenRouter not configured")
        base = self.cfg.openrouter_base_url.rstrip("/")
        r = httpx.get(
            f"{base}/auth/key",
            headers=self._headers(self.cfg.openrouter_api_key),
            timeout=30.0,
        )
        r.raise_for_status()
        data = r.json().get("data", {})
        limit = data.get("limit")
        usage = data.get("usage") or 0.0
        return {
            "usage_usd": usage,
            "limit_usd": limit,
            "balance_usd": (limit - usage) if limit is not None else None,
            "is_free_tier": data.get("is_free_tier"),
        }

    def list_models(self) -> list[dict[str, Any]]:
        """OpenRouter /models catalog (sync source, `handlers.go:3176-3287`)."""
        base, key = self._base()
        r = httpx.get(f"{base}/models", headers=self._headers(key), timeout=60.0)
        r.raise_for_status()
        return r.json().get("data", [])
