from .alerts import AlertMonitor, snapshot_status
from .metrics import Metrics
from .recorder import (
    AnomalyMonitor,
    CompileLedger,
    FlightRecorder,
    get_compile_ledger,
    get_recorder,
    set_compile_ledger,
    set_recorder,
)
from .telegram import TelegramGateway
from .tracing import Span, Tracer, current_traceparent, get_tracer, set_tracer

__all__ = [
    "AlertMonitor",
    "AnomalyMonitor",
    "CompileLedger",
    "FlightRecorder",
    "Metrics",
    "Span",
    "TelegramGateway",
    "Tracer",
    "current_traceparent",
    "get_compile_ledger",
    "get_recorder",
    "get_tracer",
    "set_compile_ledger",
    "set_recorder",
    "set_tracer",
    "snapshot_status",
]
