from .alerts import AlertMonitor, snapshot_status
from .metrics import Metrics
from .telegram import TelegramGateway

__all__ = ["AlertMonitor", "Metrics", "TelegramGateway", "snapshot_status"]
