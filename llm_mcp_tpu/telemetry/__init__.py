from .metrics import Metrics

__all__ = ["Metrics"]
