from .alerts import AlertMonitor, snapshot_status
from .metrics import Metrics
from .telegram import TelegramGateway
from .tracing import Span, Tracer, current_traceparent, get_tracer, set_tracer

__all__ = [
    "AlertMonitor",
    "Metrics",
    "Span",
    "TelegramGateway",
    "Tracer",
    "current_traceparent",
    "get_tracer",
    "set_tracer",
    "snapshot_status",
]
