"""Prometheus metrics registry.

Parity: reference `core/internal/metrics/metrics.go:10-115` — same 11
collector names/labels so existing dashboards keep working, plus TPU-native
additions (engine slot occupancy, decode throughput, TTFT).

The reference's `llmcore_jobs_created_total` was declared but never
incremented (dead metric, SURVEY §5); here it is wired up at submit.
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
    CONTENT_TYPE_LATEST,
)


class Metrics:
    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        r = self.registry

        # -- reference-parity collectors (metrics.go:10-115) --
        self.embedding_requests = Counter(
            "llmcore_embedding_requests_total",
            "Embedding requests",
            ["model", "device", "status"],
            registry=r,
        )
        self.embedding_duration = Histogram(
            "llmcore_embedding_duration_seconds",
            "Embedding request duration",
            ["model"],
            buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60),
            registry=r,
        )
        self.embedding_input_tokens = Counter(
            "llmcore_embedding_input_tokens_total",
            "Embedding input tokens",
            ["model"],
            registry=r,
        )
        self.jobs_created = Counter(
            "llmcore_jobs_created_total", "Jobs created", ["kind"], registry=r
        )
        self.devices_online = Gauge(
            "llmcore_devices_online", "Devices online", registry=r
        )
        self.discovery_runs = Counter(
            "llmcore_discovery_runs_total", "Discovery runs", ["status"], registry=r
        )
        self.discovery_duration = Histogram(
            "llmcore_discovery_duration_seconds",
            "Discovery run duration",
            registry=r,
        )
        self.chat_requests = Counter(
            "llmcore_chat_requests_total",
            "Chat requests",
            ["model", "provider", "status"],
            registry=r,
        )
        self.chat_duration = Histogram(
            "llmcore_chat_duration_seconds",
            "Chat request duration",
            ["model", "provider"],
            buckets=(0.5, 1, 2.5, 5, 10, 30, 60, 120),
            registry=r,
        )
        self.chat_tokens = Counter(
            "llmcore_chat_tokens_total",
            "Chat tokens",
            ["model", "provider", "direction"],
            registry=r,
        )
        self.chat_cost_usd = Counter(
            "llmcore_chat_cost_usd_total",
            "Chat cost USD",
            ["model", "provider"],
            registry=r,
        )
        self.openrouter_balance = Gauge(
            "llmcore_openrouter_balance_usd", "OpenRouter balance", registry=r
        )

        # -- TPU-native additions --
        self.engine_slots_in_use = Gauge(
            "llmtpu_engine_slots_in_use", "Generation engine slots occupied", registry=r
        )
        self.engine_tps = Gauge(
            "llmtpu_engine_decode_tok_per_s",
            "Decode tokens/sec over the last 10s window",
            registry=r,
        )
        self.chat_ttft = Histogram(
            "llmtpu_chat_ttft_seconds",
            "Time to first token",
            ["model"],
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10),
            registry=r,
        )
        # Fed from trace spans (tracing.py observer wired in CoreServer):
        # stage ∈ {queue_wait, route, rpc, prefill, decode}.
        self.stage_duration = Histogram(
            "llmtpu_stage_duration_seconds",
            "Per-request stage latency, derived from trace spans",
            ["stage"],
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60),
            registry=r,
        )
        # Token-budget scheduler (executor/scheduler.py): the live per-round
        # prefill token budget, how full decode dispatches run, and how often
        # the TTFT deadline demanded more prefill than the fairness cap
        # allows (starvation — raise TPU_TARGET_TTFT_MS, add capacity, or
        # shed load; doc/performance.md).
        self.sched_prefill_token_budget = Gauge(
            "llmtpu_sched_prefill_token_budget",
            "Prefill token budget of the engine's most recent scheduling decision",
            registry=r,
        )
        self.sched_decode_occupancy = Gauge(
            "llmtpu_sched_decode_batch_occupancy",
            "Active decode rows / max_slots in the most recent dispatch",
            registry=r,
        )
        self.sched_starved_rounds = Counter(
            "llmtpu_sched_starved_rounds_total",
            "Rounds where the TTFT deadline needed more prefill tokens than the fairness cap",
            registry=r,
        )
        # Self-speculative decoding (executor/engine.py draft-and-verify,
        # TPU_SPEC knobs; doc/performance.md). Per-engine labels match the
        # scheduler gauges' wiring in api/server.py engines_info.
        self.spec_accept_rate = Gauge(
            "llmtpu_spec_accept_rate",
            "Accepted / drafted speculative tokens (cumulative ratio)",
            ["engine"],
            registry=r,
        )
        self.spec_tok_per_call = Gauge(
            "llmtpu_spec_tok_per_verify_call",
            "Tokens emitted per speculative verify dispatch (cumulative ratio)",
            ["engine"],
            registry=r,
        )
        self.spec_drafted_tokens = Counter(
            "llmtpu_spec_drafted_tokens_total",
            "Draft tokens proposed by the n-gram drafter",
            ["engine"],
            registry=r,
        )
        self.spec_emitted_tokens = Counter(
            "llmtpu_spec_emitted_tokens_total",
            "Tokens emitted by speculative verify rounds (accepted + final samples)",
            ["engine"],
            registry=r,
        )
        # HBM-aware KV pool (executor/memory.py, TPU_KV_HOST_OFFLOAD):
        # headroom is the fraction of shed-free capacity left (0 = the API
        # is shedding); the counters are advanced by delta from the engines'
        # cumulative totals in api/server.py engines_info, like the
        # scheduler/speculation bridges above.
        self.kv_pool_headroom = Gauge(
            "llmtpu_kv_pool_headroom",
            "Fraction of admission capacity remaining before load shedding",
            ["engine"],
            registry=r,
        )
        self.kv_preempted = Counter(
            "llmtpu_kv_preempt_total",
            "Slots preempted and offloaded to host memory",
            ["engine"],
            registry=r,
        )
        self.kv_restored = Counter(
            "llmtpu_kv_restore_total",
            "Preempted slots restored from host memory",
            ["engine"],
            registry=r,
        )
        self.kv_shed = Counter(
            "llmtpu_kv_shed_total",
            "Requests shed above the admission watermark (429 or deferred claim)",
            ["engine"],
            registry=r,
        )
        # Paged KV block economy (executor/paging.py, TPU_KV_BLOCK_TOKENS):
        # gauges read straight from paging_stats(); the COW counter is
        # bridged by delta like the pool counters above. sharing_ratio is
        # logical/physical blocks — >1 means prefix sharing is multiplying
        # capacity; leaks must stay 0 (perf_gate hard-fails on it).
        self.kv_blocks_used = Gauge(
            "llmtpu_kv_blocks_used",
            "Physical KV blocks with a live refcount",
            ["engine"],
            registry=r,
        )
        self.kv_block_sharing = Gauge(
            "llmtpu_kv_block_sharing_ratio",
            "Logical / physical KV blocks (prefix-sharing multiplier)",
            ["engine"],
            registry=r,
        )
        self.kv_cow_copies = Counter(
            "llmtpu_kv_cow_copies_total",
            "Boundary blocks copied-on-write at shared-prefix admission",
            ["engine"],
            registry=r,
        )
        self.kv_block_leaks = Gauge(
            "llmtpu_kv_block_leaks",
            "Blocks the paging ledger audit flags as leaked/double-freed (must be 0)",
            ["engine"],
            registry=r,
        )

        # -- KV migration (executor/migration.py) --
        self.kv_migrated_out = Counter(
            "llmtpu_kv_migrate_out_total",
            "Snapshots exported to another engine (drain or prefill handoff)",
            ["engine"],
            registry=r,
        )
        self.kv_migrated_in = Counter(
            "llmtpu_kv_migrate_in_total",
            "Snapshots imported and restored from another engine",
            ["engine"],
            registry=r,
        )
        self.kv_migrate_bytes = Counter(
            "llmtpu_kv_migrate_bytes_total",
            "Wire bytes of exported KV migration payloads",
            ["engine"],
            registry=r,
        )
        self.kv_migrate_requeues = Counter(
            "llmtpu_kv_migrate_requeue_total",
            "Queued requests re-homed to an idle engine without KV transfer",
            registry=r,
        )
        self.kv_migration_headroom_delta = Gauge(
            "llmtpu_kv_migration_headroom_delta",
            "Max-min kv_headroom spread across local engines (drain trigger signal)",
            registry=r,
        )

        # -- Prefix-locality routing / fleet prefix tier (routing/prefix.py,
        # TPU_PREFIX_ROUTE / TPU_PREFIX_FETCH_MIN_TOKENS; doc/performance.md).
        # outcome: local = the serving engine already held the longest known
        # prefix; fetch = a peer's chain was pulled over PrefixFetch and
        # admitted pin-only; miss = nobody held a usable prefix.
        self.route_prefix_hit = Counter(
            "llmtpu_route_prefix_hit_total",
            "Prefix-locality routing decisions by outcome",
            ["outcome"],
            registry=r,
        )
        self.route_prefix_matched_tokens = Histogram(
            "llmtpu_route_prefix_matched_tokens",
            "Prompt tokens covered by a resident (or fetched) prefix chain at route time",
            buckets=(32, 64, 128, 256, 512, 1024, 2048, 4096, 8192),
            registry=r,
        )
        # Engine-side tier counters, advanced by delta from prefix_tier_stats()
        # in api/server.py engines_info like the pool/paging bridges above.
        self.prefix_tier_exports = Counter(
            "llmtpu_prefix_tier_exports_total",
            "Prefix chains exported to peers over the PrefixFetch RPC",
            ["engine"],
            registry=r,
        )
        self.prefix_tier_imports = Counter(
            "llmtpu_prefix_tier_imports_total",
            "Peer prefix chains imported and pinned into the local cache",
            ["engine"],
            registry=r,
        )
        self.prefix_tier_bytes = Counter(
            "llmtpu_prefix_tier_bytes_total",
            "Wire bytes of prefix-tier payloads by direction",
            ["engine", "direction"],
            registry=r,
        )
        self.prefix_tier_rejects = Counter(
            "llmtpu_prefix_tier_import_rejects_total",
            "Peer prefix payloads rejected (geometry mismatch, no budget, bad header)",
            ["engine"],
            registry=r,
        )

        # -- Flight recorder / anomaly dumps / compile ledger --
        # (telemetry/recorder.py, TPU_FLIGHT knobs; doc/observability.md).
        # The recorder itself is stdlib-only, so all Prometheus bridging
        # happens here + in api/server.py engines_info, by delta like the
        # pool/paging/migration counters above.
        self.flight_events = Counter(
            "llmtpu_flight_events_total",
            "Step events accepted into the flight-recorder ring (process-wide)",
            registry=r,
        )
        self.flight_dropped = Gauge(
            "llmtpu_flight_dropped_events",
            "Events dropped while the ring was frozen mid-dump (must be 0)",
            registry=r,
        )
        self.anomaly_dumps = Counter(
            "llmtpu_anomaly_dumps_total",
            "Anomaly-triggered flight-ring journal dumps",
            ["engine", "detector"],
            registry=r,
        )
        self.watchdog_transitions = Counter(
            "llmtpu_watchdog_transitions_total",
            "Engine watchdog state transitions "
            "(compile_grace / stalled / shed / shed_in_grace / recovered)",
            ["engine", "state"],
            registry=r,
        )
        # Fed from CompileLedger.drain_fresh() at engines_info refresh:
        # one observation per jit/bucket compile on the serve path. hit is
        # the persistent-cache heuristic (wall < TPU_COMPILE_HIT_S).
        self.compile_seconds = Histogram(
            "llmtpu_compile_seconds",
            "Wall time of serve-path executable compiles, per phase and cache outcome",
            ["engine", "phase", "hit"],
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 20, 40, 80, 160),
            registry=r,
        )

        # -- Perf observatory (telemetry/perf.py, TPU_PERF_SAMPLE /
        # TPU_TARGET_ITL_MS; doc/observability.md). ITL samples are drained
        # from each engine's observatory at engines_info refresh (exactly
        # once, like compile_seconds); goodput/roofline gauges read straight
        # from perf_stats(); the sampled phase-walls counters advance by
        # delta like the pool/paging bridges above.
        self.itl_seconds = Histogram(
            "llmtpu_itl_seconds",
            "Inter-token latency (TPOT): per-token share of each emission round's wall gap",
            ["engine"],
            buckets=(0.002, 0.005, 0.01, 0.02, 0.04, 0.08, 0.15, 0.3, 0.6, 1.2, 2.5, 5),
            registry=r,
        )
        self.goodput_tok_per_s = Gauge(
            "llmtpu_goodput_tok_per_s",
            "Tokens/s from requests meeting the joint TTFT+ITL SLO (60s window)",
            ["engine"],
            registry=r,
        )
        self.goodput_ratio = Gauge(
            "llmtpu_goodput_ratio",
            "SLO-conforming / total finished tokens (cumulative)",
            ["engine"],
            registry=r,
        )
        # per-tenant split of the same ledgers (model zoo tenancy): series
        # only materialize for tenants that actually sent traffic, so the
        # single-tenant scrape surface is unchanged
        self.goodput_tok_per_s_tenant = Gauge(
            "llmtpu_goodput_tok_per_s_tenant",
            "Per-tenant tokens/s from requests meeting the joint SLO (60s window)",
            ["engine", "tenant"],
            registry=r,
        )
        self.goodput_ratio_tenant = Gauge(
            "llmtpu_goodput_ratio_tenant",
            "Per-tenant SLO-conforming / finished tokens (cumulative)",
            ["engine", "tenant"],
            registry=r,
        )
        self.tenant_shed_total = Counter(
            "llmtpu_tenant_shed_total",
            "Admission 429s charged to a tenant (quota or capacity shed)",
            ["engine", "tenant"],
            registry=r,
        )
        self.decode_mfu = Gauge(
            "llmtpu_decode_mfu",
            "Model FLOPs utilization of sampled decode rounds vs TPU_PEAK_TFLOPS",
            ["engine"],
            registry=r,
        )
        self.decode_mbu = Gauge(
            "llmtpu_decode_mbu",
            "HBM bandwidth utilization of sampled decode rounds vs TPU_PEAK_HBM_GBPS",
            ["engine"],
            registry=r,
        )
        self.perf_phase_seconds = Counter(
            "llmtpu_perf_phase_seconds_total",
            "Sampled engine-loop wall seconds by dispatch phase and bucket "
            "(host staging / device compute / scheduler wait)",
            ["engine", "phase", "bucket"],
            registry=r,
        )
        # latency waterfall (telemetry/workload.py): each finished request's
        # wall decomposed into an exact stage partition; cumulative per-stage
        # seconds advance by delta in the engines_info bridge (server.py)
        self.latency_stage_seconds = Counter(
            "llmtpu_latency_stage_seconds",
            "Finished-request wall seconds by waterfall stage (admit_wait / "
            "shed / prefill_queue / prefill_compute / decode / stall / preempt)",
            ["engine", "stage"],
            registry=r,
        )

    def render(self) -> tuple[bytes, str]:
        return generate_latest(self.registry), CONTENT_TYPE_LATEST
