"""Telegram alert gateway.

Role parity: reference `telemetry/llm_telemetry/telegram_gateway.py:46-170,
213-237` — a thin client over the Telegram Bot API with sendMessage /
editMessageText, plus rate-limit (HTTP 429 `retry_after`) handling. The
reference also supports a telegram-mcp sidecar route; here that generalizes to
an injectable transport so tests (and alternative gateways) plug in without
network access.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from collections.abc import Callable
from typing import Any

log = logging.getLogger("telemetry.telegram")

# transport(url, payload, timeout) -> (status_code, response_json)
Transport = Callable[[str, dict[str, Any], float], tuple[int, dict[str, Any]]]


def _urllib_transport(url: str, payload: dict[str, Any], timeout: float) -> tuple[int, dict[str, Any]]:
    data = json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:  # noqa: S310
            return r.status, json.loads(r.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode() or "{}")
        except Exception:
            body = {}
        return e.code, body


class TelegramGateway:
    """Bot-API client: send or edit alert messages, tolerate rate limits.

    Reference behavior re-created (`telegram_gateway.py:104-170`):
    - sendMessage with HTML parse mode and disabled link previews;
    - editMessageText when a message_id is supplied (used for rolling
      status messages);
    - on 429, honor `parameters.retry_after` once, then give up quietly —
      alerting must never take the monitor loop down.
    """

    def __init__(
        self,
        bot_token: str,
        chat_id: str,
        transport: Transport | None = None,
        timeout: float = 10.0,
    ):
        self.bot_token = bot_token
        self.chat_id = chat_id
        self.transport = transport or _urllib_transport
        self.timeout = timeout

    @property
    def enabled(self) -> bool:
        return bool(self.bot_token and self.chat_id)

    def _call(self, method: str, payload: dict[str, Any]) -> dict[str, Any] | None:
        url = f"https://api.telegram.org/bot{self.bot_token}/{method}"
        for attempt in (0, 1):
            try:
                status, body = self.transport(url, payload, self.timeout)
            except Exception as e:  # network failure: log, never raise
                log.warning("telegram %s failed: %s", method, e)
                return None
            if status == 429 and attempt == 0:
                retry_after = 1.0
                params = body.get("parameters")
                if isinstance(params, dict):
                    try:
                        retry_after = float(params.get("retry_after", 1))
                    except (TypeError, ValueError):
                        pass
                time.sleep(min(retry_after, 30.0))
                continue
            if status >= 400:
                log.warning("telegram %s -> %s: %s", method, status, body.get("description"))
                return None
            return body
        return None

    def send(self, text: str) -> int | None:
        """Send a message; returns message_id for later edits."""
        if not self.enabled:
            return None
        body = self._call(
            "sendMessage",
            {
                "chat_id": self.chat_id,
                "text": text,
                "parse_mode": "HTML",
                "disable_web_page_preview": True,
            },
        )
        if body and isinstance(body.get("result"), dict):
            return body["result"].get("message_id")
        return None

    def edit(self, message_id: int, text: str) -> bool:
        if not self.enabled:
            return False
        body = self._call(
            "editMessageText",
            {
                "chat_id": self.chat_id,
                "message_id": message_id,
                "text": text,
                "parse_mode": "HTML",
                "disable_web_page_preview": True,
            },
        )
        return body is not None
