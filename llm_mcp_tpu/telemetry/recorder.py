"""Flight recorder, anomaly-triggered dumps, and the compile ledger.

Traces (tracing.py) answer "where did *this request* spend its time";
Prometheus (metrics.py) answers "how is the fleet doing on average".
Neither answers the post-mortem question: *what was the serve loop doing,
step by step, in the seconds before it misbehaved?*  This module is that
missing layer — a bounded ring of structured step events that every hot
subsystem appends to, frozen and journaled to disk the moment an anomaly
detector fires, plus a compile ledger that records every jit/bucket
compile (the ROADMAP item-5 cold-start baseline).

Like tracing.py this module is deliberately dependency-free (stdlib only)
and must never import `executor`, `api`, `jax`, or any other subsystem:
the instrumented layers import *us*; consumers (alert pipeline, on-demand
profiler capture) attach via callbacks instead of being imported here.
`tests/test_recorder.py` pins that contract.

Model
-----
An *event* is a tuple ``(seq, ts, etype, trace_id, fields)``:

  seq       monotonic step sequence (process-wide, from itertools.count —
            a single CPython bytecode op, so the hot path needs no lock)
  ts        wall-clock seconds
  etype     short event kind: admit / budget / chunk / pf_rag (packed
            ragged prefill, with true/padded token fields) / verify /
            decode / fused / fused_rag (ragged fused step) / preempt /
            offload / restore / cow / pin / unpin / snap (paged ledger
            snapshot for preempt/offload) / pg_tbl (device
            block-table reset/rebuild, with the shared-row count) /
            pg_cow (physical boundary-block copy: pool row -> identity
            home) / prefix_out (fleet prefix-tier chain export, with
            token + byte counts) / prefix_in (pin-only prefix-tier
            import from a peer) / migrate_out / migrate_in / shed /
            watchdog /
            compile / perf (sampled host/device/wait phase timing from
            the perf observatory) / anomaly / profile / wl (workload
            capture: one record per finished admitted request —
            telemetry/workload.py) / wf (latency-waterfall stage marks:
            per-request admit_wait/shed/prefill_queue/prefill_compute/
            decode/stall/preempt milliseconds) / wu (one warmup-planner
            AOT compile: phase, key, wall, outcome) / warmup (readiness
            state transition: cold / first_token_ready / fully_warm —
            executor/warmup.py) / zoo (model-zoo catalog change:
            registration with residency — executor/zoo.py) / swap_in /
            swap_out (zoo residency moves, with byte counts and wall
            seconds: page parked host weights into HBM / park a resident
            engine's tree back to host RAM) / cn_cmp (one constraint
            compile at admission: cache miss flag, automaton states,
            wall — llm_mcp_tpu/constrain) / cnstep (one grammar-masked
            single-step decode round, with row count) / cn_spec (one
            constrained speculative verify round: drafted vs accepted
            token counts under per-position masks)
  trace_id  the request's 32-hex trace id ("" for engine-global events) —
            a dump stitches directly into /v1/traces
  fields    flat dict of scalars (or None)

The ring is a preallocated list; `event()` writes one slot with a single
item-assignment (atomic under the GIL) and never blocks, allocates
bounded memory, and never touches a lock.  `dump()` freezes appends just
long enough to copy the ring (microseconds), then journals the copy as
JSONL off to disk; events arriving while frozen are *counted as dropped*
rather than queued — the dropped counter is the health signal bench.py
and the perf gate watch (`recorder_dropped_events` must stay 0).

Enablement follows tracing.py: on by default, `TPU_FLIGHT=0` disables
(checked per event, so the knob works on a live process and `=0` is a
true no-op — no ring writes, no dumps, no detector state).

Knobs: `TPU_FLIGHT` (default 1), `TPU_FLIGHT_RING` (ring capacity,
default 8192), `TPU_FLIGHT_DIR` (journal directory), and
`TPU_FLIGHT_DUMP_INTERVAL_S` (min seconds between anomaly dumps,
default 10).  `TPU_COMPILE_HIT_S` tunes the compile ledger's
cache-hit heuristic.  `TPU_FLIGHT_PROFILE_STEPS` is read by the engine
(the jax.profiler hook lives there, not here).
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable

__all__ = [
    "AnomalyMonitor",
    "CompileLedger",
    "DecodeStallDetector",
    "FlightRecorder",
    "ITLDegradationDetector",
    "PagedLeakDetector",
    "PingPongDetector",
    "ShedDuringGraceDetector",
    "SpecCollapseDetector",
    "TTFTBurnDetector",
    "get_compile_ledger",
    "get_recorder",
    "set_compile_ledger",
    "set_recorder",
]

DEFAULT_RING = 8192
DEFAULT_DUMP_INTERVAL_S = 10.0
# Persistent-compilation-cache hits deserialize in well under this; real
# XLA compiles of serve-path executables take multiples of it.
DEFAULT_HIT_THRESHOLD_S = 0.25

EVENT_KEYS = ("seq", "ts", "etype", "trace_id", "fields")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FlightRecorder:
    """Bounded lock-free ring of step events + freeze-and-journal dumps."""

    def __init__(
        self,
        capacity: int | None = None,
        dump_dir: str | None = None,
        dump_interval_s: float | None = None,
    ):
        self.capacity = max(16, capacity if capacity is not None
                            else _env_int("TPU_FLIGHT_RING", DEFAULT_RING))
        self.dump_dir = dump_dir or os.environ.get("TPU_FLIGHT_DIR") or os.path.join(
            tempfile.gettempdir(), "llmtpu-flight"
        )
        self.dump_interval_s = (
            dump_interval_s if dump_interval_s is not None
            else _env_float("TPU_FLIGHT_DUMP_INTERVAL_S", DEFAULT_DUMP_INTERVAL_S)
        )
        # Preallocated ring. The hot path does ONE item-assignment into it;
        # list item assignment is atomic under the GIL, so no lock and no
        # allocation beyond the event tuple itself.
        self._ring: list[tuple | None] = [None] * self.capacity
        self._seq = itertools.count()  # next(counter) is a single atomic op
        self._frozen = False           # set only inside dump()'s copy window
        self._dropped = 0
        self._dumps = 0
        self._last_dump_ts = 0.0
        self._last_dump_path = ""
        self._dump_lock = threading.Lock()   # dump/snapshot only — never event()
        self._on_dump: list[Callable[[dict], None]] = []

    # -- enablement --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        """Dynamic so TPU_FLIGHT can be flipped on a live process."""
        return os.environ.get("TPU_FLIGHT", "1").strip().lower() not in (
            "0", "false", "off", "no",
        )

    # -- hot path ----------------------------------------------------------

    def event(self, etype: str, trace_id: str = "", **fields: Any) -> None:
        """Append one step event. Never blocks, never raises, never locks:
        when the ring is frozen mid-dump the event is dropped and counted
        (the perf gate hard-fails on a nonzero drop count, so the freeze
        window is sized in microseconds)."""
        if not self.enabled:
            return
        if self._frozen:
            self._dropped += 1
            return
        seq = next(self._seq)
        self._ring[seq % self.capacity] = (
            seq, time.time(), etype, trace_id, fields or None,
        )

    # -- read side ---------------------------------------------------------

    def _copy(self) -> list[tuple]:
        """Ring contents in sequence order. Tuples are immutable and slots
        are replaced whole, so a plain list() copy yields only intact
        events (possibly spanning a wrap — sorting by seq fixes order)."""
        rows = [r for r in list(self._ring) if r is not None]
        rows.sort(key=lambda r: r[0])
        return rows

    def snapshot(self, limit: int = 0, etype: str = "") -> list[dict[str, Any]]:
        """Newest-last event dicts for /v1/debug/flight (no freeze)."""
        rows = self._copy()
        if etype:
            rows = [r for r in rows if r[2] == etype]
        if limit > 0:
            rows = rows[-limit:]
        return [dict(zip(EVENT_KEYS, r)) for r in rows]

    def events_total(self) -> int:
        """Sequence high-water mark == events accepted so far."""
        # itertools.count has no peek; track via a throwaway clone of the
        # ring head instead: the max seq present, +1. Empty ring → 0.
        rows = [r for r in list(self._ring) if r is not None]
        return (max(r[0] for r in rows) + 1) if rows else 0

    @property
    def dropped_events(self) -> int:
        return self._dropped

    def stats(self) -> dict[str, Any]:
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "events_total": self.events_total(),
            "dropped_events": self._dropped,
            "dumps": self._dumps,
            "last_dump_ts": self._last_dump_ts,
            "last_dump_path": self._last_dump_path,
        }

    # -- dumps -------------------------------------------------------------

    def add_dump_callback(self, fn: Callable[[dict], None]) -> None:
        """fn(info) fires after each journal lands on disk. Exceptions are
        swallowed. The alert pipeline and the engine's on-demand profiler
        capture attach here so this module stays import-free."""
        if fn not in self._on_dump:
            self._on_dump.append(fn)

    def remove_dump_callback(self, fn: Callable[[dict], None]) -> None:
        if fn in self._on_dump:
            self._on_dump.remove(fn)

    def dump(self, reason: str, detector: str = "", force: bool = False) -> str | None:
        """Freeze-copy-unfreeze the ring, then journal the copy as JSONL.

        The freeze covers only the in-memory copy (a list() of the ring),
        not the disk write — appenders racing the copy are counted as
        dropped rather than blocked.  Rate-limited by dump_interval_s
        unless force=True.  Returns the journal path, or None when
        disabled / rate-limited / the disk said no."""
        if not self.enabled:
            return None
        with self._dump_lock:
            now = time.time()
            if not force and now - self._last_dump_ts < self.dump_interval_s:
                return None
            self._frozen = True
            try:
                rows = self._copy()
            finally:
                self._frozen = False
            self._last_dump_ts = now
            self._dumps += 1
            path = os.path.join(
                self.dump_dir,
                f"flight-{time.strftime('%Y%m%d-%H%M%S', time.gmtime(now))}"
                f"-{self._dumps:04d}.jsonl",
            )
            header = {
                "kind": "flight_dump",
                "ts": now,
                "reason": reason,
                "detector": detector,
                "events": len(rows),
                "dropped_events": self._dropped,
                "capacity": self.capacity,
            }
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                with open(path, "w", encoding="utf-8") as f:
                    f.write(json.dumps(header) + "\n")
                    for r in rows:
                        f.write(json.dumps(dict(zip(EVENT_KEYS, r))) + "\n")
            except OSError:
                return None
            self._last_dump_path = path
        info = dict(header, path=path)
        for fn in list(self._on_dump):
            try:
                fn(info)
            except Exception:  # noqa: BLE001 — callbacks never break dumps
                pass
        return path


# -- anomaly detectors ------------------------------------------------------
# Pure state machines over scalar signals: observe(...) returns a reason
# string on the rising edge and None otherwise. Each latches after firing
# and re-arms only when its signal recovers, so one anomaly *episode*
# produces exactly one dump however often the engine polls.


class DecodeStallDetector:
    """Decode cadence stopped while work is in flight. The gap threshold is
    the larger of an absolute floor and a multiple of the scheduler's
    decode-round EMA, so slow-but-moving big batches don't false-positive."""

    name = "decode_stall"

    def __init__(self, min_gap_s: float = 2.0, ema_mult: float = 20.0):
        self.min_gap_s = min_gap_s
        self.ema_mult = ema_mult
        self._latched = False

    def observe(self, gap_s: float, ema_s: float, busy: int) -> str | None:
        stalled = busy > 0 and gap_s > max(self.min_gap_s, self.ema_mult * ema_s)
        if not stalled:
            self._latched = False
            return None
        if self._latched:
            return None
        self._latched = True
        return (f"decode cadence stalled: {gap_s:.2f}s since last round "
                f"(ema {ema_s * 1000:.0f}ms, {busy} in flight)")


class TTFTBurnDetector:
    """K consecutive TTFT samples over M× the TPU_TARGET_TTFT_MS SLO."""

    name = "ttft_burn"

    def __init__(self, target_ms: float, mult: float = 3.0, k: int = 4):
        self.target_ms = target_ms
        self.mult = mult
        self.k = max(1, k)
        self._over = 0
        self._latched = False

    def observe(self, ttft_ms: float) -> str | None:
        if self.target_ms <= 0:
            return None
        if ttft_ms <= self.mult * self.target_ms:
            self._over = 0
            self._latched = False
            return None
        self._over += 1
        if self._over < self.k or self._latched:
            return None
        self._latched = True
        return (f"TTFT SLO burn: {self._over} consecutive samples over "
                f"{self.mult:g}x target ({ttft_ms:.0f}ms vs {self.target_ms:.0f}ms)")


class SpecCollapseDetector:
    """Speculative accept rate collapsed over a window of verify rounds
    (the drafter is burning verify budget for nothing)."""

    name = "spec_collapse"

    def __init__(self, window: int = 32, min_rate: float = 0.05,
                 min_drafted: int = 64):
        self.window = deque(maxlen=max(4, window))
        self.min_rate = min_rate
        self.min_drafted = min_drafted
        self._latched = False

    def observe(self, drafted: int, accepted: int) -> str | None:
        if drafted <= 0:
            return None
        self.window.append((drafted, accepted))
        d = sum(w[0] for w in self.window)
        a = sum(w[1] for w in self.window)
        if d < self.min_drafted:
            return None
        rate = a / d
        if rate >= self.min_rate:
            self._latched = False
            return None
        if self._latched:
            return None
        self._latched = True
        return (f"speculative accept collapse: {rate:.1%} over last "
                f"{len(self.window)} verify rounds ({a}/{d})")


class PagedLeakDetector:
    """Paged-block leak count grew (audit() found unreferenced blocks).
    Re-fires only on further growth, not on a stable nonzero count."""

    name = "paged_leak"

    def __init__(self):
        self._high = 0

    def observe(self, leak_count: int) -> str | None:
        if leak_count <= self._high:
            if leak_count == 0:
                self._high = 0
            return None
        prev, self._high = self._high, leak_count
        return f"paged block leak growth: {prev} -> {leak_count} leaked blocks"


class PingPongDetector:
    """The same request migrated more than `max_hops` times inside
    `window_s` — the drain policy is shuttling KV back and forth."""

    name = "migration_pingpong"

    def __init__(self, max_hops: int = 2, window_s: float = 60.0,
                 max_tracked: int = 512):
        self.max_hops = max(1, max_hops)
        self.window_s = window_s
        self._hops: dict[str, deque] = {}
        self._order: deque = deque(maxlen=max_tracked)
        self._fired: set[str] = set()

    def observe(self, request_id: str, now: float | None = None) -> str | None:
        now = time.time() if now is None else now
        dq = self._hops.get(request_id)
        if dq is None:
            self._hops[request_id] = dq = deque()
            self._order.append(request_id)
            while len(self._hops) > self._order.maxlen:
                old = self._order.popleft()
                self._hops.pop(old, None)
                self._fired.discard(old)
        dq.append(now)
        while dq and now - dq[0] > self.window_s:
            dq.popleft()
        if len(dq) <= self.max_hops or request_id in self._fired:
            return None
        self._fired.add(request_id)
        return (f"migration ping-pong: request {request_id} moved "
                f"{len(dq)} times in {self.window_s:.0f}s")


class ITLDegradationDetector:
    """Windowed mean inter-token latency breached M× the TPU_TARGET_ITL_MS
    SLO. TTFTBurnDetector's decode-side sibling: the burn case it catches
    is tokens still flowing but *slowly* — a decode stall never trips
    (cadence stops entirely), yet users see exactly this as sluggish
    streaming. Fed per-round (itl_ms = round gap / tokens learned), it
    needs min_samples before judging so one coalesced round can't fire it."""

    name = "itl_degradation"

    def __init__(self, target_ms: float, mult: float = 3.0,
                 window: int = 64, min_samples: int = 16):
        self.target_ms = target_ms
        self.mult = mult
        self.window = deque(maxlen=max(4, window))
        self.min_samples = max(1, min_samples)
        self._latched = False

    def observe(self, itl_ms: float) -> str | None:
        if self.target_ms <= 0:
            return None
        self.window.append(itl_ms)
        if len(self.window) < self.min_samples:
            return None
        mean = sum(self.window) / len(self.window)
        if mean <= self.mult * self.target_ms:
            self._latched = False
            return None
        if self._latched:
            return None
        self._latched = True
        return (f"ITL degradation: mean {mean:.1f}ms over last "
                f"{len(self.window)} rounds vs {self.mult:g}x target "
                f"({self.target_ms:.0f}ms)")


class ShedDuringGraceDetector:
    """Load was shed while the watchdog's compile-grace window was active —
    the engine dropped work because of a *compile*, not a wedge. One fire
    per grace episode."""

    name = "shed_in_grace"

    def __init__(self):
        self._latched = False

    def observe(self, in_grace: bool, shed: int) -> str | None:
        if not in_grace:
            self._latched = False
            return None
        if shed <= 0 or self._latched:
            return None
        self._latched = True
        return f"shed {shed} request(s) during compile grace window"


class AnomalyMonitor:
    """Routes raw engine signals to the detector set; on a rising edge it
    journals the flight ring, appends to the anomaly history, and fires
    observer callbacks (the engine bridges these to the alert pipeline
    and the on-demand profiler)."""

    def __init__(
        self,
        recorder: FlightRecorder,
        detectors: list | None = None,
        history: int = 64,
        target_ttft_ms: float | None = None,
        target_itl_ms: float | None = None,
    ):
        self.recorder = recorder
        if detectors is None:
            if target_ttft_ms is None:
                target_ttft_ms = _env_float("TPU_TARGET_TTFT_MS", 0.0)
            if target_itl_ms is None:
                target_itl_ms = _env_float("TPU_TARGET_ITL_MS", 0.0)
            detectors = [
                DecodeStallDetector(),
                TTFTBurnDetector(target_ms=target_ttft_ms),
                ITLDegradationDetector(target_ms=target_itl_ms),
                SpecCollapseDetector(),
                PagedLeakDetector(),
                PingPongDetector(),
                ShedDuringGraceDetector(),
            ]
        self._detectors = {d.name: d for d in detectors}
        self._history: deque = deque(maxlen=max(4, history))
        self._counts: dict[str, int] = {}
        self._callbacks: list[Callable[[dict], None]] = []

    def add_callback(self, fn: Callable[[dict], None]) -> None:
        if fn not in self._callbacks:
            self._callbacks.append(fn)

    def signal(self, kind: str, **fields: Any) -> dict[str, Any] | None:
        """Feed one signal sample to detector `kind`. Returns the anomaly
        record on a rising edge, else None. Unknown kinds and disabled
        recorders are no-ops so call sites need no guards."""
        det = self._detectors.get(kind)
        if det is None or not self.recorder.enabled:
            return None
        try:
            reason = det.observe(**fields)
        except TypeError:
            return None  # malformed signal never breaks the serve loop
        if not reason:
            return None
        return self._fire(kind, reason)

    def _fire(self, kind: str, reason: str) -> dict[str, Any]:
        self.recorder.event("anomaly", detector=kind, reason=reason)
        path = self.recorder.dump(reason=reason, detector=kind)
        entry = {
            "ts": time.time(),
            "detector": kind,
            "reason": reason,
            "journal": path or "",
        }
        self._history.append(entry)
        self._counts[kind] = self._counts.get(kind, 0) + 1
        for fn in list(self._callbacks):
            try:
                fn(entry)
            except Exception:  # noqa: BLE001
                pass
        return entry

    def history(self, limit: int = 20) -> list[dict[str, Any]]:
        items = list(self._history)
        return items[-max(1, int(limit)):][::-1]

    def stats(self) -> dict[str, Any]:
        return {
            "dumps_total": sum(self._counts.values()),
            "by_detector": dict(self._counts),
            "last": self._history[-1] if self._history else None,
        }


# -- compile ledger ---------------------------------------------------------


class CompileLedger:
    """Every jit/bucket compile on the serve path, as (phase, bucket key,
    wall seconds, cache hit/miss). Entries land in a bounded deque; per-key
    aggregates build the queryable table /v1/debug/compiles serves; the
    metrics layer drains new entries into `llmtpu_compile_seconds`.

    Hit/miss is a wall-time heuristic: jax's persistent compilation cache
    deserializes in well under `hit_threshold_s` while a real XLA compile
    of a serve executable takes multiples of it (`TPU_COMPILE_HIT_S`
    tunes the split; an explicit hit= wins when the caller knows)."""

    def __init__(self, max_entries: int = 512,
                 hit_threshold_s: float | None = None):
        self.hit_threshold_s = (
            hit_threshold_s if hit_threshold_s is not None
            else _env_float("TPU_COMPILE_HIT_S", DEFAULT_HIT_THRESHOLD_S)
        )
        self._lock = threading.Lock()
        self._entries: deque = deque(maxlen=max(16, max_entries))
        self._by_key: dict[str, dict[str, Any]] = {}
        self._fresh: deque = deque(maxlen=max(16, max_entries))
        self._total_s = 0.0

    def observe(self, phase: str, key: str, wall_s: float,
                hit: bool | None = None, src: str = "serve") -> dict[str, Any]:
        """`src` is provenance: which path paid (or skipped) this compile —
        "serve" (first real dispatch), "warmup" (AOT warmup planner), or
        "import" (a warmup-pack plan entry adopted without compiling).
        Per-entry so /v1/debug/compiles can show whether the serve path
        ever ate a cold compile that warmup should have absorbed."""
        if hit is None:
            hit = wall_s < self.hit_threshold_s
        entry = {
            "ts": time.time(),
            "phase": phase,
            "key": key,
            "wall_s": round(float(wall_s), 6),
            "hit": bool(hit),
            "src": str(src),
        }
        with self._lock:
            self._entries.append(entry)
            self._fresh.append(entry)
            self._total_s += wall_s
            agg = self._by_key.get(key)
            if agg is None:
                self._by_key[key] = agg = {
                    "key": key, "phase": phase, "count": 0,
                    "hits": 0, "misses": 0, "total_s": 0.0, "max_s": 0.0,
                    "by_src": {},
                }
            agg["count"] += 1
            agg["hits" if hit else "misses"] += 1
            agg["total_s"] = round(agg["total_s"] + wall_s, 6)
            agg["max_s"] = round(max(agg["max_s"], float(wall_s)), 6)
            agg.setdefault("by_src", {})
            agg["by_src"][entry["src"]] = agg["by_src"].get(entry["src"], 0) + 1
        return entry

    def table(self) -> list[dict[str, Any]]:
        """Per-bucket aggregates, costliest first."""
        with self._lock:
            rows = [dict(v) for v in self._by_key.values()]
        return sorted(rows, key=lambda r: -r["total_s"])

    def entries(self, limit: int = 100) -> list[dict[str, Any]]:
        with self._lock:
            rows = list(self._entries)
        return rows[-max(1, int(limit)):]

    def drain_fresh(self) -> list[dict[str, Any]]:
        """Entries observed since the last drain — the metrics bridge feeds
        these to the llmtpu_compile_seconds histogram exactly once."""
        with self._lock:
            rows = list(self._fresh)
            self._fresh.clear()
        return rows

    def stats(self) -> dict[str, Any]:
        with self._lock:
            n = len(self._entries)
            hits = sum(1 for e in self._entries if e["hit"])
            shapes = len(self._by_key)
            total = self._total_s
            by_src: dict[str, int] = {}
            for e in self._entries:
                s = e.get("src", "serve")
                by_src[s] = by_src.get(s, 0) + 1
        return {
            "entries": n,
            "hits": hits,
            "misses": n - hits,
            "shapes": shapes,
            "total_s": round(total, 6),
            "by_src": by_src,
        }


# -- module-level defaults --------------------------------------------------
# One shared recorder + ledger per process so all engines, the API layer,
# and worker threads land events in the same ring (which /v1/debug/flight
# serves), mirroring tracing.get_tracer().

_default_recorder: FlightRecorder | None = None
_default_ledger: CompileLedger | None = None
_default_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _default_recorder
    if _default_recorder is None:
        with _default_lock:
            if _default_recorder is None:
                _default_recorder = FlightRecorder()
    return _default_recorder


def set_recorder(recorder: FlightRecorder) -> FlightRecorder:
    """Swap the process-default recorder (tests use this for isolation).
    Returns the previous recorder."""
    global _default_recorder
    with _default_lock:
        prev = _default_recorder
        _default_recorder = recorder
    return prev if prev is not None else recorder


def get_compile_ledger() -> CompileLedger:
    global _default_ledger
    if _default_ledger is None:
        with _default_lock:
            if _default_ledger is None:
                _default_ledger = CompileLedger()
    return _default_ledger


def set_compile_ledger(ledger: CompileLedger) -> CompileLedger:
    global _default_ledger
    with _default_lock:
        prev = _default_ledger
        _default_ledger = ledger
    return prev if prev is not None else ledger
