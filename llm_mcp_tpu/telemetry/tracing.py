"""Dapper-style request tracing: spans, context propagation, trace ring.

Aggregate metrics (metrics.py) say the fleet is slow; traces say *which
request* was slow and *where* — queue wait vs. route vs. prefill vs. decode
vs. cloud fallback. This module is deliberately dependency-free (stdlib
only) and must never import `executor`, `api`, or any other subsystem: the
instrumented layers import *us*, and consumers (stage histograms, slow-trace
alerts) attach via `Tracer.add_observer` instead of being imported here.

Model
-----
A *span* is a named interval with a 128-bit trace id, a 64-bit span id, an
optional parent span id, a wall-clock start and a monotonic-derived
duration, and a flat string→scalar attribute dict.  Completed spans land in
a bounded in-memory ring keyed by trace id (oldest trace evicted first);
traces are never formally "closed", which keeps the model robust to spans
arriving out of order from multiple processes and threads.

Propagation uses the W3C `traceparent` wire format
(`00-<32 hex trace id>-<16 hex span id>-01`) carried in HTTP headers, gRPC
invocation metadata, and job payloads (`payload["_traceparent"]`).

In-process, the *current* span is tracked on a module-level thread-local
stack so nested `span()` blocks parent implicitly and helpers like
`current_traceparent()` work from anywhere on the request thread.

Engine span attribute taxonomy (the executor stamps these; consumers like
`scripts/trace_dump.py` and the stage histograms key on them):

  engine.admit    request_id
  engine.prefill  request_id, prompt_tokens, ttft_ms,
                  prefill_token_budget, sched_starved_rounds
  engine.decode   request_id, completion_tokens, tok_per_s, finish_reason;
                  with self-speculative decoding on (TPU_SPEC), also
                  spec_drafted / spec_accepted — the stream's draft-and-
                  verify contribution, explaining its tok_per_s

Tracing is on by default and globally disabled with `TPU_TRACE=0`; the
check is dynamic (read per span start) so tests and operators can flip it
on a live process.  `TPU_TRACE_FILE=<path>` appends every completed span
as one JSON line (the format `scripts/trace_dump.py` reads back).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import uuid
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Callable, Iterator

__all__ = [
    "NEW_TRACE",
    "Span",
    "Tracer",
    "UNTRACED_PATHS",
    "current_span",
    "current_traceparent",
    "format_traceparent",
    "get_tracer",
    "parse_traceparent",
    "pop_span",
    "push_span",
    "set_tracer",
]

TRACEPARENT_RE = re.compile(r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")

DEFAULT_MAX_TRACES = 512
# Explicit parent sentinel: start a fresh root trace even when the calling
# thread already has an active span (HTTP dispatch uses this when no inbound
# traceparent header is present).
NEW_TRACE = object()
# Probe endpoints would otherwise evict every interesting trace from the ring.
UNTRACED_PATHS = frozenset({"/health", "/metrics"})


def _new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def parse_traceparent(value: str | None) -> tuple[str, str] | None:
    """`traceparent` header/metadata/payload value → (trace_id, span_id),
    or None when absent or malformed (malformed context starts a new trace
    rather than erroring the request)."""
    if not value:
        return None
    m = TRACEPARENT_RE.match(value.strip().lower())
    if not m:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # all-zero ids are invalid per W3C
    return trace_id, span_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


class Span:
    """One timed interval. Created via Tracer.span()/start_span(); `end()`
    computes the duration from a monotonic clock and hands the span to the
    tracer's ring + observers."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name",
        "start", "duration_s", "attrs", "status",
        "_t0", "_tracer", "_ended",
    )

    def __init__(
        self,
        tracer: "Tracer | None",
        name: str,
        trace_id: str,
        parent_id: str = "",
        attrs: dict[str, Any] | None = None,
    ):
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.name = name
        self.start = time.time()
        self.duration_s = 0.0
        self.attrs: dict[str, Any] = dict(attrs) if attrs else {}
        self.status = "ok"
        self._t0 = time.monotonic()
        self._tracer = tracer
        self._ended = False

    # -- mutation ----------------------------------------------------------

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def set_attrs(self, attrs: dict[str, Any]) -> "Span":
        self.attrs.update(attrs)
        return self

    def set_error(self, message: str) -> "Span":
        self.status = "error"
        self.attrs["error"] = message
        return self

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self.duration_s = time.monotonic() - self._t0
        if self._tracer is not None:
            self._tracer._finish(self)

    # -- context -----------------------------------------------------------

    @property
    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    def to_dict(self) -> dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "duration_s": round(self.duration_s, 6),
            "status": self.status,
            "attrs": self.attrs,
        }


class _NoopSpan(Span):
    """Returned when tracing is disabled: absorbs the full Span API, never
    reaches the ring or observers."""

    def __init__(self):
        super().__init__(None, "", "")

    def set_attr(self, key: str, value: Any) -> "Span":
        return self

    def set_attrs(self, attrs: dict[str, Any]) -> "Span":
        return self

    def set_error(self, message: str) -> "Span":
        return self

    def end(self) -> None:
        pass

    @property
    def traceparent(self) -> str:
        return ""


_NOOP = _NoopSpan()

# Module-level (not per-Tracer) so swapping the default tracer mid-session
# never orphans a thread's active span stack.
_ctx = threading.local()


def _stack() -> list[Span]:
    try:
        return _ctx.stack
    except AttributeError:
        _ctx.stack = []
        return _ctx.stack


def current_span() -> Span | None:
    """The innermost live span on this thread, or None."""
    st = _stack()
    return st[-1] if st else None


def current_traceparent() -> str:
    """Wire context for the innermost live span on this thread ("" when no
    span is active — callers propagate only truthy values)."""
    sp = current_span()
    return sp.traceparent if sp is not None else ""


def push_span(span: Span) -> None:
    """Make `span` the thread's current span (explicit-lifetime callers like
    HTTP dispatch; prefer the span() context manager)."""
    if not isinstance(span, _NoopSpan):
        _stack().append(span)


def pop_span(span: Span) -> None:
    st = _stack()
    if st and st[-1] is span:
        st.pop()
    elif span in st:  # defensive: out-of-order exit
        st.remove(span)


ParentLike = "Span | str | tuple[str, str] | None"


class Tracer:
    def __init__(
        self,
        max_traces: int = DEFAULT_MAX_TRACES,
        export_path: str | None = None,
    ):
        self.max_traces = max(1, int(max_traces))
        self._export_path = (
            export_path if export_path is not None else os.environ.get("TPU_TRACE_FILE")
        )
        self._lock = threading.Lock()
        # trace_id → list of completed span dicts, oldest trace first
        self._traces: OrderedDict[str, list[dict[str, Any]]] = OrderedDict()
        self._observers: list[Callable[[Span], None]] = []

    # -- enablement --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        """Dynamic so TPU_TRACE can be flipped on a live process."""
        return os.environ.get("TPU_TRACE", "1").strip().lower() not in (
            "0", "false", "off", "no",
        )

    # -- span creation -----------------------------------------------------

    def _resolve_parent(self, parent: Any) -> tuple[str, str]:
        """parent (Span | traceparent str | (trace_id, span_id) | None) →
        (trace_id, parent_span_id); None falls back to the thread's current
        span, else a fresh root trace."""
        if parent is NEW_TRACE:
            return _new_trace_id(), ""
        if parent is None:
            parent = current_span()
        if parent is None:
            return _new_trace_id(), ""
        if isinstance(parent, Span):
            if isinstance(parent, _NoopSpan):
                return _new_trace_id(), ""
            return parent.trace_id, parent.span_id
        if isinstance(parent, tuple):
            return parent[0], parent[1]
        ids = parse_traceparent(str(parent))
        if ids is None:
            return _new_trace_id(), ""
        return ids

    def start_span(
        self,
        name: str,
        parent: Any = None,
        attrs: dict[str, Any] | None = None,
    ) -> Span:
        """Start a span WITHOUT pushing it on the thread-local stack (for
        explicitly-managed lifetimes). Prefer the span() context manager."""
        if not self.enabled:
            return _NOOP
        trace_id, parent_id = self._resolve_parent(parent)
        return Span(self, name, trace_id, parent_id, attrs)

    @contextmanager
    def span(
        self,
        name: str,
        parent: Any = None,
        attrs: dict[str, Any] | None = None,
    ) -> Iterator[Span]:
        """Context-managed span, pushed on the thread-local stack so nested
        spans (and cross-layer helpers) parent to it implicitly."""
        sp = self.start_span(name, parent, attrs)
        if sp is _NOOP:
            yield sp
            return
        push_span(sp)
        try:
            yield sp
        except Exception as e:
            sp.set_error(f"{type(e).__name__}: {e}")
            raise
        finally:
            pop_span(sp)
            sp.end()

    def record(
        self,
        name: str,
        start: float,
        end: float,
        parent: Any = None,
        attrs: dict[str, Any] | None = None,
    ) -> Span | None:
        """Retroactively record a completed interval from wall-clock
        timestamps already measured elsewhere (the engine stamps
        created/admitted/first-token times on its own thread; spans are
        reconstructed after the fact). Returns the recorded span, or None
        when tracing is disabled or the interval is degenerate."""
        if not self.enabled or end < start:
            return None
        trace_id, parent_id = self._resolve_parent(parent)
        sp = Span(self, name, trace_id, parent_id, attrs)
        sp.start = start
        sp._ended = True
        sp.duration_s = end - start
        self._finish(sp)
        return sp

    # -- completion / storage ----------------------------------------------

    def _finish(self, span: Span) -> None:
        doc = span.to_dict()
        with self._lock:
            bucket = self._traces.get(span.trace_id)
            if bucket is None:
                self._traces[span.trace_id] = bucket = []
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            bucket.append(doc)
        for fn in list(self._observers):
            try:
                fn(span)
            except Exception:  # noqa: BLE001 — observers never break requests
                pass
        path = self._export_path
        if path:
            try:
                with self._lock:
                    with open(path, "a", encoding="utf-8") as f:
                        f.write(json.dumps(doc) + "\n")
            except OSError:
                self._export_path = None  # disk said no; stop trying

    # -- observers ---------------------------------------------------------

    def add_observer(self, fn: Callable[[Span], None]) -> None:
        """fn(span) is called after every span completes. Exceptions are
        swallowed. Used by the metrics layer (stage histograms) and the
        alert monitor (slow-trace hook) so this module stays import-free."""
        if fn not in self._observers:
            self._observers.append(fn)

    def remove_observer(self, fn: Callable[[Span], None]) -> None:
        if fn in self._observers:
            self._observers.remove(fn)

    # -- read side (/v1/traces) --------------------------------------------

    def get_trace(self, trace_id: str) -> list[dict[str, Any]]:
        """All completed spans of one trace, oldest start first."""
        with self._lock:
            spans = list(self._traces.get(trace_id) or ())
        return sorted(spans, key=lambda d: d["start"])

    def traces(self, limit: int = 50) -> list[dict[str, Any]]:
        """Newest-first trace summaries for the dashboard list view."""
        with self._lock:
            items = [(tid, list(spans)) for tid, spans in self._traces.items()]
        out = []
        for tid, spans in reversed(items[-max(1, int(limit)):]):
            if not spans:
                continue
            roots = [s for s in spans if not s["parent_id"]]
            head = min(roots or spans, key=lambda d: d["start"])
            t0 = min(s["start"] for s in spans)
            t1 = max(s["start"] + s["duration_s"] for s in spans)
            out.append({
                "trace_id": tid,
                "name": head["name"],
                "start": t0,
                "duration_s": round(t1 - t0, 6),
                "spans": len(spans),
                "status": "error" if any(s["status"] == "error" for s in spans) else "ok",
            })
        return out

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


# -- module-level default tracer ------------------------------------------
# One shared tracer per process so API threads, the engine loop, and worker
# threads all land spans in the same ring (which /v1/traces serves).

_default: Tracer | None = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Tracer()
    return _default


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-default tracer (tests use this for isolation).
    Returns the previous tracer."""
    global _default
    with _default_lock:
        prev = _default
        _default = tracer
    return prev if prev is not None else tracer
