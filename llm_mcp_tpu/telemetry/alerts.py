"""Alert monitor: DB-driven cluster health alerting.

Role parity: reference `telemetry/llm_telemetry/main.py` — an alert-only loop
(default 30 s) that reads the state database directly (never through the API)
and raises human-readable alerts through the Telegram gateway:

- device offline / recovery, computed as a diff against the previous scan's
  online-state snapshot (`main.py:101-129`);
- queue stuck: queued jobs present but nothing has started for a while;
- failed jobs in the last hour at/over ``ALERT_FAIL_THRESHOLD``
  (`main.py:87-96`), with per-job dedupe so one broken job does not re-alert
  every scan (`main.py:174-194`).

TPU-specific addition: devices whose tags carry ``hbm_gb`` report as slices,
and an engine-dead condition (device online but its generation engine stopped
reporting metrics) is surfaced as a distinct alert — the slice analog of the
reference's "Ollama up, host down" case.
"""

from __future__ import annotations

import html
import logging
import threading
import time
from collections import deque
from typing import Any

from ..state.db import Database
from .telegram import TelegramGateway
from .tracing import Span, Tracer

log = logging.getLogger("telemetry.alerts")


class AlertMonitor:
    def __init__(
        self,
        db: Database,
        gateway: TelegramGateway | None = None,
        interval_s: float = 30.0,
        fail_threshold: int = 5,
        stuck_after_s: float = 300.0,
        now_fn=time.time,
    ):
        self.db = db
        self.gateway = gateway
        self.interval_s = interval_s
        self.fail_threshold = fail_threshold
        self.stuck_after_s = stuck_after_s
        self.now = now_fn
        self._prev_online: dict[str, bool] = {}
        # insertion-ordered dedupe memory so eviction drops the OLDEST ids
        self._seen_failures: dict[str, None] = {}
        self._stuck_alerted = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # slow-trace hook: spans overrunning their deadline_s attribute are
        # queued here by the tracer observer and drained on the next scan
        self._tracer: Tracer | None = None
        self._slow_lock = threading.Lock()
        self._slow_spans: deque[tuple[str, str, float, float]] = deque(maxlen=100)
        self._seen_slow: dict[str, None] = {}

    # -- scan logic --------------------------------------------------------

    def scan_once(self) -> list[str]:
        """One pass over the DB; returns the alert lines raised."""
        alerts: list[str] = []
        alerts += self._scan_devices()
        alerts += self._scan_failed_jobs()
        alerts += self._scan_stuck_queue()
        alerts += self._scan_slow_traces()
        for a in alerts:
            log.warning("alert: %s", a)
            if self.gateway is not None:
                self.gateway.send(a)
        return alerts

    def _scan_devices(self) -> list[str]:
        alerts: list[str] = []
        rows = self.db.query("SELECT id, name, online, tags FROM devices")
        current: dict[str, bool] = {}
        for r in rows:
            dev_id = r["id"]
            online = bool(r["online"])
            current[dev_id] = online
            prev = self._prev_online.get(dev_id)
            label = html.escape(r["name"] or dev_id)
            tags = Database.from_json(r["tags"], {})
            kind = "slice" if isinstance(tags, dict) and "hbm_gb" in tags else "device"
            if prev is True and not online:
                alerts.append(f"🔴 {kind} <b>{label}</b> went offline")
            elif prev is False and online:
                alerts.append(f"🟢 {kind} <b>{label}</b> recovered")
        self._prev_online = current
        return alerts

    def _scan_failed_jobs(self) -> list[str]:
        cutoff = self.now() - 3600.0
        rows = self.db.query(
            "SELECT id, kind, error FROM jobs "
            "WHERE status='error' AND finished_at >= ? ORDER BY finished_at DESC LIMIT 200",
            (cutoff,),
        )
        fresh = [r for r in rows if r["id"] not in self._seen_failures]
        for r in fresh:
            self._seen_failures[r["id"]] = None
        # bound the dedupe memory on every scan, evicting oldest-first
        while len(self._seen_failures) > 10000:
            self._seen_failures.pop(next(iter(self._seen_failures)))
        if len(rows) >= self.fail_threshold and fresh:
            sample = "; ".join(
                html.escape(f"{r['kind']}#{r['id'][:8]}: {(r['error'] or '')[:80]}")
                for r in fresh[:3]
            )
            return [
                f"⚠️ <b>{len(rows)}</b> failed jobs in the last hour "
                f"({len(fresh)} new). Latest: {sample}"
            ]
        return []

    def _scan_stuck_queue(self) -> list[str]:
        row = self.db.query_one(
            "SELECT COUNT(*) AS n, MIN(created_at) AS oldest FROM jobs WHERE status='queued'"
        )
        n = int(row["n"]) if row else 0
        oldest = row["oldest"] if row else None
        # "stuck" means nothing is moving: old queued work AND no claim has
        # started recently (a busy queue retrying one old job is not stuck)
        recent = self.db.query_one(
            "SELECT MAX(started_at) AS last_start FROM jobs WHERE started_at IS NOT NULL"
        )
        last_start = (recent or {}).get("last_start")
        active = last_start is not None and (self.now() - float(last_start)) < self.stuck_after_s
        stuck = (
            n > 0
            and not active
            and oldest is not None
            and (self.now() - float(oldest)) > self.stuck_after_s
        )
        if stuck and not self._stuck_alerted:
            self._stuck_alerted = True
            age_min = (self.now() - float(oldest)) / 60.0
            return [f"⏳ queue stuck: <b>{n}</b> queued jobs, oldest waiting {age_min:.0f} min"]
        if not stuck and self._stuck_alerted:
            self._stuck_alerted = False
            if n == 0:
                return ["✅ queue drained"]
        return []

    # -- slow-trace hook ---------------------------------------------------

    def attach_tracer(self, tracer: Tracer) -> "AlertMonitor":
        """Observe completed spans; any span carrying a ``deadline_s``
        attribute (the end-to-end job/chat spans stamp their quality-tier
        deadline, `router.quality_deadline_s`) that overran it is raised as
        an alert on the next scan."""
        self.detach_tracer()
        self._tracer = tracer
        tracer.add_observer(self._on_span_end)
        return self

    def detach_tracer(self) -> None:
        if self._tracer is not None:
            self._tracer.remove_observer(self._on_span_end)
            self._tracer = None

    def _on_span_end(self, span: Span) -> None:
        try:
            deadline = float(span.attrs.get("deadline_s") or 0.0)
        except (TypeError, ValueError):
            return
        if deadline <= 0.0 or span.duration_s <= deadline:
            return
        with self._slow_lock:
            if span.trace_id in self._seen_slow:
                return  # one alert per trace, however many spans overrun
            self._seen_slow[span.trace_id] = None
            while len(self._seen_slow) > 10000:
                self._seen_slow.pop(next(iter(self._seen_slow)))
            self._slow_spans.append((span.trace_id, span.name, span.duration_s, deadline))

    def _scan_slow_traces(self) -> list[str]:
        with self._slow_lock:
            drained = list(self._slow_spans)
            self._slow_spans.clear()
        return [
            f"🐌 slow trace <code>{html.escape(tid)}</code> ({html.escape(name)}): "
            f"{dur:.2f}s &gt; {deadline:.0f}s deadline"
            for tid, name, dur, deadline in drained
        ]

    # -- loop --------------------------------------------------------------

    def run(self, stop: threading.Event | None = None) -> None:
        stop = stop or self._stop
        log.info("alert monitor: interval=%ss threshold=%s", self.interval_s, self.fail_threshold)
        while not stop.is_set():
            try:
                self.scan_once()
            except Exception:
                log.exception("alert scan failed")
            stop.wait(self.interval_s)

    def start(self) -> "AlertMonitor":
        self._thread = threading.Thread(target=self.run, name="alert-monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.detach_tracer()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def snapshot_status(db: Database) -> dict[str, Any]:
    """Compact cluster status line used for rolling Telegram status edits."""
    jobs = {
        r["status"]: r["n"]
        for r in db.query("SELECT status, COUNT(*) AS n FROM jobs GROUP BY status")
    }
    devices = db.query_one("SELECT COUNT(*) AS total, SUM(online) AS online FROM devices") or {}
    return {
        "jobs": jobs,
        "devices_online": int(devices.get("online") or 0),
        "devices_total": int(devices.get("total") or 0),
    }
