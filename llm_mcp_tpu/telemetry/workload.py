"""Workload capture, trace synthesis, and the per-request latency waterfall.

The perf observatory (perf.py) explains the *steady state* and the flight
recorder (recorder.py) journals the *anomaly*, but both describe traffic
someone else made up: every line of record comes from synthetic
closed-loop clients.  This module closes that gap in three pieces:

1. **Workload capture** — every admitted request that finishes becomes one
   compact schema-versioned JSONL record: arrival wall-clock, prompt token
   count plus the prefix-chain *head hashes* (the routing/prefix.py
   digests — never raw text), sampling params, output tokens, finish
   reason.  Records land in a bounded ring (dumped via
   ``/v1/debug/workload``) and optionally append-stream to the
   ``TPU_WORKLOAD_TRACE`` path.

2. **Trace tooling** — ``parse_trace`` reads a capture back (garbage lines
   are *counted as rejected*, never raised: a trace that survived a crash
   mid-line must still load), ``synth_trace`` generates seeded synthetic
   workloads (chat / embed / longctx / bursty agent tool-call loops), and
   ``prompt_text_for`` derives a deterministic prompt for a record that
   carries no raw ids — seeded from the chain head hash so prefix-sharing
   structure survives the round trip.  bench.py's ``BENCH_TRACE`` mode and
   scripts/replay.py both build their request streams from these, which is
   what makes two seeded replays byte-identical.

3. **Latency waterfall** — the per-request ledger decomposing wall time
   into stages that sum *exactly* to the measured wall by construction:

     admit_wait       created -> admitted (submit queue + admission gate)
     shed             admission-shed backoff spent before submit landed
     prefill_compute  synchronous prefill dispatch walls attributed to the
                      request (token-share of each batch/chunk dispatch)
     prefill_queue    (admitted -> first token) minus prefill_compute —
                      time the prompt sat admitted but not on the device
     decode           first token -> finish, minus stall and preempt
     stall            inter-token gaps beyond TPU_WATERFALL_STALL_MS
     preempt          wall spent preempted (snapshot parked off-slot)

   ``LatencyWaterfall`` keeps percentile windows per stage, cumulative
   per-stage seconds (the ``llmtpu_latency_stage_seconds`` delta bridge in
   api/server.py reads these), and a recent-request ring for
   ``/v1/debug/latency``.

Like tracing/recorder/perf this module is deliberately stdlib-only and
must never import ``executor``, ``api``, ``routing``, ``jax`` or any
other subsystem: the engine imports *us* and hands plain scalars/lists.
``analysis/imports_lint.py`` pins that contract.

Knobs: ``TPU_WORKLOAD`` (default 1; ``=0`` is a true no-op),
``TPU_WORKLOAD_RING`` (ring capacity, default 8192),
``TPU_WORKLOAD_TRACE`` (append-stream capture path),
``TPU_WORKLOAD_IDS`` (default 0; ``=1`` embeds raw prompt token ids in
records — required for token-identical replay, off by default because ids
are reversible to text), and ``TPU_WATERFALL_STALL_MS`` (inter-token gap
beyond which decode time counts as stall, default 250).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
from collections import deque
from typing import Any, Iterable

__all__ = [
    "AGENT_TOOL_SCHEMAS",
    "LatencyWaterfall",
    "SCHEMA_VERSION",
    "STAGES",
    "WorkloadTrace",
    "get_workload",
    "load_trace",
    "parse_trace",
    "prompt_text_for",
    "set_workload",
    "stall_threshold_s",
    "synth_trace",
]

SCHEMA_VERSION = 1
DEFAULT_RING = 8192
# head hashes only: enough chain entries to see prefix-sharing structure
# without shipping the whole boundary list for an 8k prompt
CHAIN_HEAD = 8

# Canned tool-call schemas for the synthetic agent workload. These are
# CLOSED schemas — every field is an enum or boolean, so the constraint
# automaton's accepting state has no outgoing transitions and the mask
# forces EOS there. A grammar-constrained replay therefore terminates
# with valid JSON on ANY model, which is what lets bench.py's
# schema_valid_rate gate demand exactly 1.0 (scripts/perf_gate.py).
AGENT_TOOL_SCHEMAS: tuple = (
    {"type": "object", "properties": {
        "tool": {"enum": ["search", "fetch", "calc"]},
        "urgent": {"type": "boolean"},
    }},
    {"type": "object", "properties": {
        "action": {"enum": ["read", "write", "list"]},
        "confirm": {"enum": ["yes", "no"]},
    }},
    {"type": "object", "properties": {
        "op": {"enum": ["add", "mul", "div"]},
        "commit": {"type": "boolean"},
    }},
)

STAGES = (
    "admit_wait",
    "shed",
    "prefill_queue",
    "prefill_compute",
    "decode",
    "stall",
    "preempt",
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def stall_threshold_s() -> float:
    """Inter-token gap beyond which decode wall counts as stall.

    Read per call so the knob works on a live process (recorder.py's
    enablement convention)."""
    return max(0.0, _env_float("TPU_WATERFALL_STALL_MS", 250.0)) / 1e3


def _pctl(vals: list[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


# ---------------------------------------------------------------------------
# capture


class WorkloadTrace:
    """Bounded ring of per-request workload records + optional file stream."""

    def __init__(
        self,
        capacity: int | None = None,
        trace_path: str | None = None,
        include_ids: bool | None = None,
    ):
        cap = capacity if capacity is not None else _env_int("TPU_WORKLOAD_RING", DEFAULT_RING)
        self.capacity = max(16, cap)
        # None means "read the env per record" so the knobs work live
        self._trace_path = trace_path
        self._include_ids = include_ids
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.records_total = 0
        self.file_errors = 0

    def enabled(self) -> bool:
        """TPU_WORKLOAD=0 is a true no-op (checked per record, live knob)."""
        return os.environ.get("TPU_WORKLOAD", "1") not in ("0", "false", "no", "off")

    def _want_ids(self) -> bool:
        if self._include_ids is not None:
            return self._include_ids
        return os.environ.get("TPU_WORKLOAD_IDS", "0") not in ("", "0", "false", "no", "off")

    def trace_path(self) -> str:
        if self._trace_path is not None:
            return self._trace_path
        return os.environ.get("TPU_WORKLOAD_TRACE", "")

    def record(
        self,
        *,
        ts: float,
        rid: str,
        trace_id: str = "",
        model: str = "",
        prompt_tokens: int = 0,
        chain: Iterable[tuple[int, str]] = (),
        max_tokens: int = 0,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        output_tokens: int = 0,
        finish: str = "",
        ids: Iterable[int] | None = None,
        shed_s: float = 0.0,
    ) -> dict | None:
        """Append one admitted-request record; returns it (or None when off)."""
        if not self.enabled():
            return None
        rec: dict[str, Any] = {
            "v": SCHEMA_VERSION,
            "ts": float(ts),
            "rid": str(rid),
            "trace": str(trace_id or ""),
            "model": str(model),
            "pt": int(prompt_tokens),
            "chain": [[int(n), str(h)] for n, h in list(chain)[:CHAIN_HEAD]],
            "mt": int(max_tokens),
            "temp": float(temperature),
            "top_k": int(top_k),
            "top_p": float(top_p),
            "ot": int(output_tokens),
            "fin": str(finish),
        }
        if shed_s > 0:
            rec["shed_s"] = round(float(shed_s), 6)
        if ids is not None and self._want_ids():
            rec["ids"] = [int(t) for t in ids]
        with self._lock:
            self._ring.append(rec)
            self.records_total += 1
        path = self.trace_path()
        if path:
            try:
                with open(path, "a", encoding="utf-8") as fh:
                    fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
            except OSError:
                self.file_errors += 1
        return rec

    def snapshot(self, limit: int = 200) -> list[dict]:
        """Newest-last copy of the ring tail."""
        with self._lock:
            rows = list(self._ring)
        return rows[-max(0, limit):] if limit else rows

    def stats(self) -> dict:
        with self._lock:
            ring_len = len(self._ring)
        return {
            "enabled": self.enabled(),
            "records_total": self.records_total,
            "ring": ring_len,
            "capacity": self.capacity,
            "trace_path": self.trace_path(),
            "file_errors": self.file_errors,
            "include_ids": self._want_ids(),
        }

    def dump(self, path: str) -> int:
        """Write the whole ring to `path` as JSONL; returns record count."""
        rows = self.snapshot(limit=0)
        with open(path, "w", encoding="utf-8") as fh:
            for rec in rows:
                fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        return len(rows)


_workload: WorkloadTrace | None = None
_workload_lock = threading.Lock()


def get_workload() -> WorkloadTrace:
    """Process-shared capture ring (recorder.py's get_recorder convention)."""
    global _workload
    with _workload_lock:
        if _workload is None:
            _workload = WorkloadTrace()
        return _workload


def set_workload(w: WorkloadTrace | None) -> None:
    global _workload
    with _workload_lock:
        _workload = w


# ---------------------------------------------------------------------------
# trace parsing


def _valid_record(rec: Any) -> bool:
    if not isinstance(rec, dict) or rec.get("v") != SCHEMA_VERSION:
        return False
    if not isinstance(rec.get("ts"), (int, float)):
        return False
    for key in ("pt", "mt", "ot", "top_k"):
        v = rec.get(key, 0)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            return False
    for key in ("temp", "top_p", "shed_s"):
        v = rec.get(key, 0.0)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            return False
    chain = rec.get("chain", [])
    if not isinstance(chain, list):
        return False
    for entry in chain:
        if (
            not isinstance(entry, (list, tuple))
            or len(entry) != 2
            or not isinstance(entry[0], int)
            or not isinstance(entry[1], str)
        ):
            return False
    ids = rec.get("ids")
    if ids is not None and (
        not isinstance(ids, list)
        or any(not isinstance(t, int) or isinstance(t, bool) for t in ids)
    ):
        return False
    return True


def parse_trace(lines: Iterable[str]) -> tuple[list[dict], int]:
    """(records, rejected_count) from capture JSONL lines.

    Garbage — truncated JSON, wrong schema version, non-record rows — is
    *counted*, never raised: a trace file that survived a crash mid-write
    must still replay."""
    records: list[dict] = []
    rejected = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            rejected += 1
            continue
        if _valid_record(rec):
            records.append(rec)
        else:
            rejected += 1
    return records, rejected


def load_trace(path: str) -> tuple[list[dict], int]:
    """parse_trace over a file, sorted by arrival timestamp."""
    with open(path, encoding="utf-8") as fh:
        records, rejected = parse_trace(fh)
    records.sort(key=lambda r: r["ts"])
    return records, rejected


# ---------------------------------------------------------------------------
# synthetic workload generators

_WORDS = (
    "the model reads a long context and answers with a short plan then "
    "calls a tool parses the result and continues the loop until the task "
    "is done or the budget runs out"
).split()


def _hash16(data: str) -> str:
    return hashlib.blake2b(data.encode(), digest_size=8).hexdigest()


def _synth_chain(pt: int, head_seed: str, block_tokens: int = 64) -> list[list]:
    """Deterministic chain-head boundary hashes for a synthetic prompt."""
    out: list[list] = []
    h = head_seed
    for i in range(min(CHAIN_HEAD, pt // block_tokens)):
        h = _hash16(h + str(i))
        out.append([(i + 1) * block_tokens, h])
    return out


def _mk(ts: float, i: int, kind: str, seed: int, *, pt: int, mt: int,
        temp: float, chain_seed: str) -> dict:
    return {
        "v": SCHEMA_VERSION,
        "ts": round(ts, 6),
        "rid": f"{kind[:2]}{seed:04x}{i:06x}",
        "trace": "",
        "model": "",
        "pt": pt,
        "chain": _synth_chain(pt, chain_seed),
        "mt": mt,
        "temp": temp,
        "top_k": 0,
        "top_p": 1.0,
        "ot": 0,
        "fin": "",
    }


def synth_trace(kind: str, n: int, seed: int = 0, start_ts: float = 0.0) -> list[dict]:
    """Seeded synthetic workload: same (kind, n, seed) -> byte-identical
    records, which is what makes two replays issue identical streams.

    kinds:
      chat    Poisson arrivals ~2 rps, short-to-medium prompts, sampled
      embed   dense bursts of short prompts, 1-token outputs (embedding-
              shaped traffic: all prefill, no decode)
      longctx sparse arrivals, 1k-8k prompts, short outputs
      agent   bursty tool-call loops: 3-8 requests per burst sharing one
              prefix chain (the conversation so far), think-time between.
              Each burst is one tool loop, so its records carry the SAME
              tool-call JSON schema under ``rec["schema"]`` (drawn from
              AGENT_TOOL_SCHEMAS) — bench.py's constrained sweep wraps it
              as a json_schema constraint for grammar-constrained replay
    """
    rng = random.Random((seed << 8) ^ len(kind))
    ts = float(start_ts)
    out: list[dict] = []
    if kind == "chat":
        for i in range(n):
            ts += rng.expovariate(2.0)
            out.append(_mk(ts, i, kind, seed,
                           pt=rng.randint(48, 512),
                           mt=rng.randint(32, 256),
                           temp=round(rng.uniform(0.5, 0.9), 2),
                           chain_seed=f"chat{seed}:{i}"))
    elif kind == "embed":
        i = 0
        while i < n:
            ts += rng.expovariate(0.5)
            for _ in range(min(rng.randint(8, 32), n - i)):
                ts += 0.002
                out.append(_mk(ts, i, kind, seed,
                               pt=rng.randint(16, 128), mt=1, temp=0.0,
                               chain_seed=f"embed{seed}:{i}"))
                i += 1
    elif kind == "longctx":
        for i in range(n):
            ts += rng.expovariate(0.25)
            out.append(_mk(ts, i, kind, seed,
                           pt=rng.randint(1024, 8192),
                           mt=rng.randint(32, 128),
                           temp=0.0,
                           chain_seed=f"longctx{seed}:{i}"))
    elif kind == "agent":
        i = 0
        burst = 0
        while i < n:
            ts += rng.uniform(2.0, 8.0)  # think-time between tool loops
            shared = f"agent{seed}:burst{burst}"
            # one tool per loop: every request in the burst emits a call
            # shaped by the same (closed) JSON schema
            sch = AGENT_TOOL_SCHEMAS[rng.randrange(len(AGENT_TOOL_SCHEMAS))]
            grow = 0
            for _ in range(min(rng.randint(3, 8), n - i)):
                ts += rng.uniform(0.05, 0.4)  # tool round-trip
                grow += rng.randint(64, 256)  # the loop's growing context
                rec = _mk(ts, i, kind, seed,
                          pt=256 + grow,
                          mt=rng.randint(16, 96),
                          temp=0.0,
                          chain_seed=shared)
                rec["schema"] = sch
                out.append(rec)
                i += 1
            burst += 1
    else:
        raise ValueError(f"unknown synthetic workload kind: {kind!r}"
                         " (chat/embed/longctx/agent)")
    return out


def prompt_text_for(rec: dict, words_per_token: float = 0.75) -> str:
    """Deterministic prompt text for a record that carries no raw ids.

    Seeded from the chain head hash (so records sharing a prefix chain get
    a shared textual prefix — the replay preserves prefix-cache structure)
    plus the rid for the unique tail.  Identical records -> identical
    text, which keeps two seeded replays byte-identical."""
    n_words = max(1, int(rec.get("pt", 1) * words_per_token))
    chain = rec.get("chain") or []
    parts: list[str] = []
    if chain:
        head = random.Random(chain[0][1])
        shared_words = max(1, int(n_words * min(1.0, len(chain) / CHAIN_HEAD)))
        parts.extend(_WORDS[head.randrange(len(_WORDS))] for _ in range(shared_words))
        n_words -= shared_words
    tail = random.Random(str(rec.get("rid", "")))
    parts.extend(_WORDS[tail.randrange(len(_WORDS))] for _ in range(n_words))
    return " ".join(parts)


# ---------------------------------------------------------------------------
# latency waterfall


class LatencyWaterfall:
    """Per-request latency ledger with exact-partition stages.

    The engine hands finished-request stage seconds (already clamped so
    they sum exactly to the request's measured wall); this class keeps the
    percentile windows, the cumulative per-stage totals the Prometheus
    delta bridge reads, and the recent-request ring /v1/debug/latency
    serves."""

    def __init__(self, window: int = 2048, recent: int = 128):
        self._lock = threading.Lock()
        self._windows: dict[str, deque[float]] = {
            s: deque(maxlen=window) for s in STAGES
        }
        self._total_window: deque[float] = deque(maxlen=window)
        self._stage_s: dict[str, float] = {s: 0.0 for s in STAGES}
        self._recent: deque[dict] = deque(maxlen=recent)
        self.requests = 0
        self.wall_s_total = 0.0

    def observe(
        self,
        stages: dict[str, float],
        total_s: float,
        trace_id: str = "",
        rid: str = "",
        ts: float = 0.0,
    ) -> None:
        with self._lock:
            self.requests += 1
            self.wall_s_total += max(0.0, total_s)
            self._total_window.append(max(0.0, total_s))
            row: dict[str, Any] = {
                "ts": round(ts, 6),
                "rid": rid,
                "trace": trace_id,
                "total_ms": round(total_s * 1e3, 3),
            }
            for s in STAGES:
                v = max(0.0, float(stages.get(s, 0.0)))
                self._stage_s[s] += v
                self._windows[s].append(v)
                row[f"{s}_ms"] = round(v * 1e3, 3)
            self._recent.append(row)

    def stage_seconds(self) -> dict[str, float]:
        """Cumulative seconds per stage — the engines_info delta bridge
        advances llmtpu_latency_stage_seconds from consecutive reads."""
        with self._lock:
            return dict(self._stage_s)

    def stats(self) -> dict:
        with self._lock:
            stage_s = dict(self._stage_s)
            pct = {
                s: {
                    "p50_ms": round(_pctl(list(w), 0.50) * 1e3, 3),
                    "p95_ms": round(_pctl(list(w), 0.95) * 1e3, 3),
                }
                for s, w in self._windows.items()
            }
            total_p95 = _pctl(list(self._total_window), 0.95)
            n = self.requests
            wall = self.wall_s_total
        covered = sum(stage_s.values())
        return {
            "requests": n,
            "stage_s": {s: round(v, 6) for s, v in stage_s.items()},
            "stages": pct,
            "total_p95_ms": round(total_p95 * 1e3, 3),
            "wall_s_total": round(wall, 6),
            # stages are an exact partition by construction; this ratio is
            # the acceptance check (must stay within 5% of 1.0)
            "coverage": round(covered / wall, 6) if wall > 0 else 1.0,
        }

    def recent(self, limit: int = 32) -> list[dict]:
        with self._lock:
            rows = list(self._recent)
        return rows[-max(0, limit):]
