"""`python -m llm_mcp_tpu.telemetry` — standalone alerting service.

Process parity: reference `telemetry/llm_telemetry/main.py` entrypoint (the
`llmtelemetry` compose service): connect to the state DB, loop forever raising
alerts to Telegram. Runs against the same SQLite file the core uses (WAL mode
allows concurrent readers), or any `DB_PATH` pointed at a replica.
"""

from __future__ import annotations

import logging
import os


def main() -> None:
    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO"),
        format='{"ts":"%(asctime)s","level":"%(levelname)s","logger":"%(name)s","msg":"%(message)s"}',
    )
    from ..state.db import Database
    from ..utils.config import Config
    from .alerts import AlertMonitor
    from .telegram import TelegramGateway

    cfg = Config()
    db = Database(cfg.db_path)
    gateway = TelegramGateway(cfg.telegram_bot_token, cfg.telegram_chat_id)
    if not gateway.enabled:
        logging.getLogger("main").warning(
            "TELEGRAM_BOT_TOKEN/TELEGRAM_CHAT_ID unset — alerts log-only"
        )
        gateway = None
    monitor = AlertMonitor(
        db,
        gateway=gateway,
        interval_s=cfg.telemetry_interval_s,
        fail_threshold=cfg.alert_fail_threshold,
    )
    try:
        monitor.run()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
