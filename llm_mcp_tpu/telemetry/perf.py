"""Token-timeline perf observatory: ITL/TPOT, goodput, and rooflines.

TTFT histograms say how fast the *first* token arrives; the compile ledger
says what cold dispatches cost; neither explains a steady-state regression.
This module is the third observability layer (tracing.py = per-request,
recorder.py = post-mortem, perf.py = *explanation*), with three coupled
parts:

1. **Token timelines** — the engine feeds every emission round's
   (wall gap, tokens learned) pair here, yielding per-token inter-token
   latency (ITL, a.k.a. TPOT) p50/p95/p99 over a rolling window, and a
   goodput accountant that classifies each finished request against the
   joint TTFT + ITL SLO (`TPU_TARGET_TTFT_MS` / `TPU_TARGET_ITL_MS`):
   `goodput_tok_per_s` counts only tokens from SLO-conforming requests,
   the metric DistServe/Sarathi-class serving work optimizes for, vs the
   raw tok/s the dashboard has always shown.

2. **Phase attribution** — every Nth dispatch (`TPU_PERF_SAMPLE`, dynamic;
   0 disables) the engine brackets one round with a device sync and
   reports {host staging, device compute, scheduler wait} walls per
   dispatch phase. The CompileLedger times only *first* dispatches; this
   is the steady-state complement, and it is sampled precisely so the
   pipelined loop only pays a serializing block_until_ready once per N
   rounds.

3. **Rooflines** — analytical FLOPs and HBM-byte cost models per cache
   layout (bf16/int8 × GQA/MLA, including the fused int8 layout's scale
   pseudo-head rows and the paged path's block-table gathers) turn the
   sampled decode device time into MFU/MBU gauges against the chip peaks
   (`TPU_PEAK_TFLOPS` / `TPU_PEAK_HBM_GBPS`, default TPU v5e). The live
   `decode_mbu` number is ROADMAP item 5's "layers_gbps toward 650"
   microbench, continuously measured on the serve path. All four layouts
   are evaluated against the same measured token rate — the non-active
   rows are the what-if column (what would this traffic cost under the
   other cache layouts); `active` marks the one the engine actually runs.

Like tracing.py and recorder.py this module is stdlib-only and must never
import `executor`, `api`, `jax`, or `numpy` — the engine imports *us* and
hands plain scalars in (`tests/test_perf.py` pins the contract).

`DISPATCH_PHASES` below is the registry of record for the serve path's
steady-state dispatch phases: the lint in tests/test_perf.py asserts every
phase string the engine feeds `_compile_obs` is either listed here (and
therefore has a recorder etype and a cost model) or in
`AUX_COMPILE_PHASES` (compile-ledger-only paths with no steady-state
cadence to sample).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any

__all__ = [
    "AUX_COMPILE_PHASES",
    "CACHE_LAYOUTS",
    "DISPATCH_PHASES",
    "ModelShape",
    "PerfObservatory",
    "WARMUP_PHASES",
    "decode_flops_per_token",
    "decode_hbm_bytes_per_token",
    "kv_bytes_per_token",
    "layout_name",
    "phase_cost",
    "prefill_flops_per_token",
]

# Steady-state dispatch phases: every one has a CompileLedger phase string,
# a flight-recorder etype, and a cost model in PHASE_COSTS (lint-enforced).
DISPATCH_PHASES = (
    "admit", "chunk", "cnstep", "decode", "fused", "fused_rag", "pf_rag",
    "verify",
)
# Compile-ledger-only phases: rare, data-dependent dispatches (COW block
# copies, pool offload staging, host-payload pool puts on the fleet
# prefix-tier import path, preemption restore) with no steady-state
# cadence worth sampling — the ledger's first-dispatch wall is the story.
AUX_COMPILE_PHASES = ("cow", "pool_put", "pool_put_host", "restore")
# Warmup-plannable subset of the dispatch surface (executor/warmup.py):
# phases whose jit argument shapes are a pure function of the engine's
# config (so an AOT lower().compile() can be synthesized from a shape key
# alone, without live traffic). fused/fused_rag/verify depend on the live
# fill mix and speculation state — the planner lists their ledger-observed
# keys but marks them unplannable (they compile on first real dispatch).
WARMUP_PHASES = ("admit", "chunk", "decode", "pf_rag")

CACHE_LAYOUTS = ("gqa_bf16", "gqa_int8", "mla_bf16", "mla_int8")

DEFAULT_PERF_SAMPLE = 32
DEFAULT_TARGET_ITL_MS = 0.0  # no ITL SLO unless configured
# TPU v5e chip peaks; override for other generations via env.
DEFAULT_PEAK_TFLOPS = 197.0
DEFAULT_PEAK_HBM_GBPS = 819.0
_SCALE_BYTES = 4  # per-(head, token) quantization scale, f32


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def layout_name(mla: bool, int8: bool) -> str:
    return ("mla" if mla else "gqa") + ("_int8" if int8 else "_bf16")


@dataclass(frozen=True)
class ModelShape:
    """The scalar facts the cost models need, decoupled from ModelConfig so
    this module never imports the models package (which pulls jax)."""

    dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    head_dim: int
    param_count: int
    # MLA latent dims; 0 when the model is plain GQA
    kv_lora_rank: int = 0
    qk_rope_head_dim: int = 0

    @classmethod
    def from_config(cls, cfg: Any) -> "ModelShape":
        """Duck-typed: accepts any object with ModelConfig's fields."""
        hd = getattr(cfg, "head_dim", 0) or cfg.dim // cfg.n_heads
        return cls(
            dim=cfg.dim,
            n_layers=cfg.n_layers,
            n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_kv_heads,
            head_dim=hd,
            param_count=int(cfg.param_count()),
            kv_lora_rank=getattr(cfg, "kv_lora_rank", 0) or 0,
            qk_rope_head_dim=getattr(cfg, "qk_rope_head_dim", 0) or 0,
        )


# -- cost models -------------------------------------------------------------


def _fused_scale_bytes(n_kv_heads: int, head_dim: int) -> int:
    """Per-token bytes of the fused int8 layout's packed scales: one f32
    scale per (k|v, kv-head, token), packed into pseudo-head rows of
    head_dim int8 lanes riding in the payload tensor — storage rounds up
    to whole rows, so the cost is the padded row width, not the scalars."""
    raw = 2 * n_kv_heads * _SCALE_BYTES
    rows = -(-raw // max(1, head_dim))
    return rows * head_dim


def kv_bytes_per_token(shape: ModelShape, layout: str) -> float:
    """KV-cache bytes ONE token occupies across all layers under `layout`.
    GQA stores k+v per kv-head; MLA stores one shared latent row
    (kv_lora_rank + rope key dims). int8 layouts add per-token scales —
    for fused GQA int8, padded to pseudo-head row granularity."""
    L = shape.n_layers
    if layout.startswith("mla"):
        latent = shape.kv_lora_rank + shape.qk_rope_head_dim
        if layout.endswith("int8"):
            return float(L * (latent + _SCALE_BYTES))
        return float(L * latent * 2)  # bf16 latents
    per_tok = 2 * shape.n_kv_heads * shape.head_dim
    if layout.endswith("int8"):
        return float(
            L * (per_tok + _fused_scale_bytes(shape.n_kv_heads, shape.head_dim))
        )
    return float(L * per_tok * 2)  # bf16 k+v


def decode_flops_per_token(shape: ModelShape, layout: str, ctx: float) -> float:
    """FLOPs to decode one token at mean context `ctx`: 2 FLOPs per weight
    (every parameter does one MAC) plus attention. GQA attention is QK^T +
    PV over the context (2 matmuls × 2 FLOPs/MAC per head); MLA's absorbed
    decode form runs both against the latent cache, so the per-head width
    is (kv_lora_rank + rope) for scores and kv_lora_rank for values.
    Layout quantization changes bytes, not FLOPs."""
    weights = 2.0 * shape.param_count
    if layout.startswith("mla"):
        score_w = shape.kv_lora_rank + shape.qk_rope_head_dim
        attn = 2.0 * shape.n_layers * shape.n_heads * ctx * (
            score_w + shape.kv_lora_rank
        )
    else:
        attn = 4.0 * shape.n_layers * shape.n_heads * shape.head_dim * ctx
    return weights + attn


def decode_hbm_bytes_per_token(
    shape: ModelShape,
    layout: str,
    ctx: float,
    rows: float,
    *,
    paged: bool = False,
    block_tokens: int = 16,
    weight_bytes_per_param: float = 1.0,
) -> float:
    """HBM bytes moved per decoded token: the full weight stream amortized
    over the batch rows (one stream serves every row of a step), the KV
    read of the row's whole context, the one-token KV append, and — paged —
    the block-table index gathers (one i32 per block per layer, the
    indirection the kernels' scalar-prefetch path reads)."""
    rows = max(1.0, rows)
    weights = shape.param_count * weight_bytes_per_param / rows
    kv_tok = kv_bytes_per_token(shape, layout)
    kv_read = ctx * kv_tok
    kv_write = kv_tok
    table = 0.0
    if paged:
        table = shape.n_layers * 4.0 * (ctx / max(1, block_tokens))
    return weights + kv_read + kv_write + table


def prefill_flops_per_token(shape: ModelShape, layout: str, ctx: float) -> float:
    """Prefill costs the same weight FLOPs per token; causal attention over
    a prompt averages half the final context per token."""
    return decode_flops_per_token(shape, layout, ctx / 2.0)


def _prefill_cost(shape, layout, ctx, rows, paged, block_tokens):
    flops = prefill_flops_per_token(shape, layout, ctx)
    # prefill is compute-bound: weights stream once per chunk, KV is
    # written (not read back) for every token
    byts = (
        shape.param_count / max(1.0, rows * max(ctx, 1.0))
        + kv_bytes_per_token(shape, layout)
    )
    return flops, byts


def _decode_cost(shape, layout, ctx, rows, paged, block_tokens):
    return (
        decode_flops_per_token(shape, layout, ctx),
        decode_hbm_bytes_per_token(
            shape, layout, ctx, rows, paged=paged, block_tokens=block_tokens
        ),
    )


# Registry of record: one analytical (flops, bytes) model per steady-state
# dispatch phase. verify is decode-shaped (one fused step over the drafted
# tokens); the prefill family shares the chunk model.
PHASE_COSTS = {
    "admit": _prefill_cost,
    "chunk": _prefill_cost,
    "pf_rag": _prefill_cost,
    "cnstep": _decode_cost,  # one masked decode step: decode-shaped
    "decode": _decode_cost,
    "fused": _decode_cost,
    "fused_rag": _decode_cost,
    "verify": _decode_cost,
}


def phase_cost(
    phase: str,
    shape: ModelShape,
    layout: str,
    *,
    ctx: float,
    rows: float,
    paged: bool = False,
    block_tokens: int = 16,
) -> tuple[float, float]:
    """(flops_per_token, hbm_bytes_per_token) for one dispatch phase."""
    return PHASE_COSTS[phase](shape, layout, ctx, rows, paged, block_tokens)


def _pctl(vals: list[float], q: float) -> float:
    """Nearest-rank percentile (matches engine.ttft_percentiles)."""
    if not vals:
        return 0.0
    n = len(vals)
    return vals[max(0, min(n - 1, int(n * q + 0.5) - 1))]


class PerfObservatory:
    """Per-process-engine perf state: ITL window, goodput ledger, sampled
    phase attribution, and the roofline evaluation. All writers are the
    engine thread; readers (API/dashboard/bench) take the same small lock
    the writers do, so snapshots are internally consistent."""

    def __init__(
        self,
        shape: ModelShape | None = None,
        *,
        active_layout: str = "gqa_bf16",
        paged: bool = False,
        block_tokens: int = 16,
        weight_bytes_per_param: float = 1.0,
        target_ttft_ms: float | None = None,
        target_itl_ms: float | None = None,
        itl_window: int = 4096,
    ):
        self.shape = shape
        self.active_layout = active_layout
        self.paged = paged
        self.block_tokens = max(1, int(block_tokens))
        self.weight_bytes_per_param = weight_bytes_per_param
        self.target_ttft_ms = (
            _env_float("TPU_TARGET_TTFT_MS", 0.0)
            if target_ttft_ms is None else target_ttft_ms
        )
        self.target_itl_ms = (
            _env_float("TPU_TARGET_ITL_MS", DEFAULT_TARGET_ITL_MS)
            if target_itl_ms is None else target_itl_ms
        )
        self._lock = threading.Lock()
        # rolling per-token ITL seconds (percentile window) + a fresh queue
        # the Prometheus bridge drains exactly once per sample
        self._itl = deque(maxlen=max(64, itl_window))
        self._itl_fresh = deque(maxlen=8192)
        self._itl_count = 0
        self._itl_sum_s = 0.0
        # goodput ledger: lifetime counters + a rolling (ts, tokens, good)
        # window for the live tok/s split
        self.finished_requests = 0
        self.good_requests = 0
        self.finished_tokens = 0
        self.good_tokens = 0
        self._finish_window = deque(maxlen=4096)
        # per-tenant goodput ledgers (model zoo tenancy): tenant id ->
        # the same lifetime counters + rolling window as the engine-wide
        # ledger, plus the tenant's shed (429) count. Empty until a
        # request actually carries a tenant id — the single-tenant path
        # allocates nothing here.
        self._tenants: dict[str, dict[str, Any]] = {}
        # sampled phase attribution {phase: {host_s, device_s, wait_s,
        # samples, tokens}} — tokens only for the decode family (the MFU/MBU
        # denominator); dispatch counters drive the every-Nth cadence
        self._phases = {
            p: {"host_s": 0.0, "device_s": 0.0, "wait_s": 0.0,
                "samples": 0, "tokens": 0}
            for p in DISPATCH_PHASES
        }
        self._dispatches = {p: 0 for p in DISPATCH_PHASES}
        # live decode-shape EMAs feeding the roofline (mean context, rows)
        self._ctx_ema = 0.0
        self._rows_ema = 0.0

    # -- sampling cadence --------------------------------------------------

    @property
    def sample_every(self) -> int:
        """Dynamic (like TPU_FLIGHT): flip TPU_PERF_SAMPLE on a live
        process. 0 disables sampling entirely."""
        return _env_int("TPU_PERF_SAMPLE", DEFAULT_PERF_SAMPLE)

    def should_sample(self, phase: str) -> bool:
        """True on every Nth dispatch of `phase`. The caller must skip
        first dispatches (those belong to the CompileLedger — a compile
        wall in the steady-state attribution would swamp it)."""
        n = self.sample_every
        c = self._dispatches.get(phase)
        if c is None:
            return False
        self._dispatches[phase] = c + 1
        return n > 0 and (c + 1) % n == 0

    # -- token timelines ---------------------------------------------------

    def observe_itl(self, gap_s: float, n_tokens: int) -> float:
        """One emission round for one request: `n_tokens` arrived
        `gap_s` after the request's previous emission (or its first
        token). Tokens learned in one fetch share the gap evenly — the
        engine only syncs once per round, so a finer split would be
        fiction. Returns the per-token ITL in seconds."""
        if n_tokens <= 0:
            return 0.0
        itl = max(0.0, gap_s) / n_tokens
        with self._lock:
            # cap the fan-out so one giant coalesced round can't flood the
            # percentile window with identical samples
            for _ in range(min(n_tokens, 64)):
                self._itl.append(itl)
                self._itl_fresh.append(itl)
            self._itl_count += n_tokens
            self._itl_sum_s += max(0.0, gap_s)
        return itl

    def itl_percentiles(self) -> dict[str, float]:
        with self._lock:
            vals = sorted(self._itl)
            n = self._itl_count
        return {
            "p50_ms": _pctl(vals, 0.50) * 1e3,
            "p95_ms": _pctl(vals, 0.95) * 1e3,
            "p99_ms": _pctl(vals, 0.99) * 1e3,
            "samples": float(n),
        }

    def drain_itl(self) -> list[float]:
        """ITL samples (seconds) since the last drain — the metrics bridge
        observes each into llmtpu_itl_seconds exactly once."""
        with self._lock:
            vals = list(self._itl_fresh)
            self._itl_fresh.clear()
        return vals

    # -- goodput accounting ------------------------------------------------

    def finish_request(
        self, ttft_ms: float, itl_mean_ms: float, tokens: int,
        tenant: str = "",
    ) -> bool:
        """Classify one finished request against the joint SLO. A target of
        0 means that axis is unconstrained (matching TTFTBurnDetector's
        no-SLO convention). Returns whether the request was good. A
        non-empty `tenant` also lands the request in that tenant's ledger
        (per-tenant goodput for the zoo scheduler and /v1/debug/perf)."""
        good = (
            (self.target_ttft_ms <= 0 or ttft_ms <= self.target_ttft_ms)
            and (self.target_itl_ms <= 0 or itl_mean_ms <= self.target_itl_ms)
        )
        with self._lock:
            self.finished_requests += 1
            self.finished_tokens += tokens
            if good:
                self.good_requests += 1
                self.good_tokens += tokens
            self._finish_window.append((time.time(), tokens, good))
            if tenant:
                t = self._tenant_locked(tenant)
                t["finished_requests"] += 1
                t["finished_tokens"] += tokens
                if good:
                    t["good_requests"] += 1
                    t["good_tokens"] += tokens
                t["window"].append((time.time(), tokens, good))
        return good

    def _tenant_locked(self, tenant: str) -> dict[str, Any]:
        """Ledger for `tenant`, created on first touch. Caller holds the
        lock."""
        t = self._tenants.get(tenant)
        if t is None:
            t = {
                "finished_requests": 0, "good_requests": 0,
                "finished_tokens": 0, "good_tokens": 0, "shed": 0,
                "window": deque(maxlen=1024),
            }
            self._tenants[tenant] = t
        return t

    def note_tenant_shed(self, tenant: str, n: int = 1) -> None:
        """A per-tenant admission 429: quota or capacity shed charged to
        `tenant`'s ledger (surfaced in /v1/debug/perf and the
        llmtpu_tenant_shed_total metric)."""
        if not tenant:
            return
        with self._lock:
            self._tenant_locked(tenant)["shed"] += int(n)

    def tenant_goodput(self, window_s: float = 60.0) -> dict[str, dict[str, float]]:
        """Per-tenant goodput split, same shape as `goodput()` per entry
        plus the tenant's shed count. Empty dict when no request ever
        carried a tenant id."""
        now = time.time()
        out: dict[str, dict[str, float]] = {}
        with self._lock:
            for name, t in self._tenants.items():
                rows = [r for r in t["window"] if now - r[0] <= window_s]
                ftok, gtok = t["finished_tokens"], t["good_tokens"]
                out[name] = {
                    "goodput_tok_per_s": sum(
                        tok for _, tok, g in rows if g
                    ) / window_s,
                    "raw_finished_tok_per_s": sum(
                        tok for _, tok, _ in rows
                    ) / window_s,
                    "good_requests": float(t["good_requests"]),
                    "finished_requests": float(t["finished_requests"]),
                    "good_tokens": float(gtok),
                    "finished_tokens": float(ftok),
                    "goodput_ratio": (gtok / ftok) if ftok else 1.0,
                    "shed": float(t["shed"]),
                }
        return out

    def tenant_goodput_ratios(self) -> dict[str, float]:
        """Lifetime goodput_ratio per tenant — the SLO-debt signal the
        engine's preemption victim selection reads every preempt
        decision (cheap: no window scan)."""
        with self._lock:
            return {
                name: (t["good_tokens"] / t["finished_tokens"])
                if t["finished_tokens"] else 1.0
                for name, t in self._tenants.items()
            }

    def goodput(self, window_s: float = 60.0) -> dict[str, float]:
        now = time.time()
        with self._lock:
            rows = [r for r in self._finish_window if now - r[0] <= window_s]
            fin, good_r = self.finished_requests, self.good_requests
            ftok, gtok = self.finished_tokens, self.good_tokens
        raw = sum(t for _, t, _ in rows) / window_s
        good = sum(t for _, t, g in rows if g) / window_s
        return {
            "goodput_tok_per_s": good,
            "raw_finished_tok_per_s": raw,
            "good_requests": float(good_r),
            "finished_requests": float(fin),
            "good_tokens": float(gtok),
            "finished_tokens": float(ftok),
            "goodput_ratio": (gtok / ftok) if ftok else 1.0,
            "target_ttft_ms": self.target_ttft_ms,
            "target_itl_ms": self.target_itl_ms,
        }

    # -- sampled phase attribution ----------------------------------------

    def observe_phase(
        self,
        phase: str,
        host_s: float,
        device_s: float,
        wait_s: float = 0.0,
        *,
        tokens: int = 0,
        rows: int = 0,
        ctx_mean: float = 0.0,
    ) -> None:
        rec = self._phases.get(phase)
        if rec is None:
            return
        with self._lock:
            rec["host_s"] += max(0.0, host_s)
            rec["device_s"] += max(0.0, device_s)
            rec["wait_s"] += max(0.0, wait_s)
            rec["samples"] += 1
            rec["tokens"] += max(0, tokens)
            if rows > 0:
                self._rows_ema = (
                    rows if self._rows_ema == 0.0
                    else 0.8 * self._rows_ema + 0.2 * rows
                )
            if ctx_mean > 0:
                self._ctx_ema = (
                    ctx_mean if self._ctx_ema == 0.0
                    else 0.8 * self._ctx_ema + 0.2 * ctx_mean
                )

    def phase_attribution(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {
                p: {
                    "host_s": round(r["host_s"], 6),
                    "device_s": round(r["device_s"], 6),
                    "wait_s": round(r["wait_s"], 6),
                    "samples": float(r["samples"]),
                    "tokens": float(r["tokens"]),
                }
                for p, r in self._phases.items()
            }

    # -- roofline ----------------------------------------------------------

    def _decode_device_tok_per_s(self) -> float:
        """Sampled decode-family token rate while the device was actually
        computing — the roofline's measured input."""
        with self._lock:
            dev = sum(
                self._phases[p]["device_s"]
                for p in ("decode", "fused", "fused_rag")
            )
            tok = sum(
                self._phases[p]["tokens"]
                for p in ("decode", "fused", "fused_rag")
            )
        return tok / dev if dev > 0 else 0.0

    def roofline(self) -> dict[str, Any]:
        """MFU/MBU for every cache layout at the live decode shape. The
        measured token rate comes from the sampled device walls; the four
        layouts share it so the non-active rows read as what-ifs."""
        peak_flops = _env_float("TPU_PEAK_TFLOPS", DEFAULT_PEAK_TFLOPS) * 1e12
        peak_bw = _env_float("TPU_PEAK_HBM_GBPS", DEFAULT_PEAK_HBM_GBPS) * 1e9
        tok_s = self._decode_device_tok_per_s()
        ctx = self._ctx_ema or 1.0
        rows = self._rows_ema or 1.0
        out: dict[str, Any] = {
            "peak_tflops": peak_flops / 1e12,
            "peak_hbm_gbps": peak_bw / 1e9,
            "device_tok_per_s": round(tok_s, 1),
            "ctx_mean": round(ctx, 1),
            "rows_mean": round(rows, 2),
            "active_layout": self.active_layout,
            "layouts": {},
        }
        if self.shape is None:
            return out
        for layout in CACHE_LAYOUTS:
            wb = (
                self.weight_bytes_per_param
                if layout == self.active_layout else
                (1.0 if layout.endswith("int8") else 2.0)
            )
            flops, byts = (
                decode_flops_per_token(self.shape, layout, ctx),
                decode_hbm_bytes_per_token(
                    self.shape, layout, ctx, rows,
                    paged=self.paged, block_tokens=self.block_tokens,
                    weight_bytes_per_param=wb,
                ),
            )
            out["layouts"][layout] = {
                "flops_per_token": flops,
                "hbm_bytes_per_token": byts,
                "arith_intensity": flops / byts if byts else 0.0,
                "mfu": (flops * tok_s / peak_flops) if peak_flops else 0.0,
                "mbu": (byts * tok_s / peak_bw) if peak_bw else 0.0,
                "active": layout == self.active_layout,
            }
        act = out["layouts"][self.active_layout]
        out["decode_mfu"] = round(act["mfu"], 4)
        out["decode_mbu"] = round(act["mbu"], 4)
        return out

    # -- the /v1/debug/perf document --------------------------------------

    def stats(self) -> dict[str, Any]:
        return {
            "sample_every": float(self.sample_every),
            "itl": self.itl_percentiles(),
            "itl_mean_ms": (
                self._itl_sum_s / self._itl_count * 1e3
                if self._itl_count else 0.0
            ),
            "goodput": self.goodput(),
            "tenants": self.tenant_goodput(),
            "phases": self.phase_attribution(),
            "roofline": self.roofline(),
        }
