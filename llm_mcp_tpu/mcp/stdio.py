"""MCP stdio server: JSON-RPC 2.0 over stdin/stdout, no SDK dependency.

Role parity: reference `fastmcp/server.py` runs under the FastMCP framework,
which handles the Model Context Protocol plumbing. This environment has no
MCP SDK, so the protocol subset MCP hosts actually use for tool servers is
implemented directly:

- `initialize` / `notifications/initialized` handshake,
- `tools/list` → the 12 tool specs,
- `tools/call` → dispatch into `tools.py`, results wrapped as text content,
- `ping`, graceful EOF shutdown.

Wire format: one JSON-RPC message per line (newline-delimited JSON), the
standard stdio transport framing of MCP.
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Any, TextIO

from .tools import TOOLS, TOOLS_BY_NAME, ToolContext

log = logging.getLogger("mcp.stdio")

PROTOCOL_VERSION = "2025-03-26"
SERVER_INFO = {"name": "llm-mcp-tpu", "version": "0.1.0"}

# JSON-RPC error codes
PARSE_ERROR = -32700
INVALID_REQUEST = -32600
METHOD_NOT_FOUND = -32601
INVALID_PARAMS = -32602
INTERNAL_ERROR = -32603


class MCPStdioServer:
    def __init__(self, ctx: ToolContext, stdin: TextIO | None = None, stdout: TextIO | None = None):
        self.ctx = ctx
        self.stdin = stdin or sys.stdin
        self.stdout = stdout or sys.stdout
        self.initialized = False

    # -- transport ---------------------------------------------------------

    def _send(self, msg: dict[str, Any]) -> None:
        self.stdout.write(json.dumps(msg, ensure_ascii=False) + "\n")
        self.stdout.flush()

    def _reply(self, req_id: Any, result: Any) -> None:
        self._send({"jsonrpc": "2.0", "id": req_id, "result": result})

    def _error(self, req_id: Any, code: int, message: str) -> None:
        self._send({"jsonrpc": "2.0", "id": req_id, "error": {"code": code, "message": message}})

    # -- dispatch ----------------------------------------------------------

    def handle_message(self, msg: dict[str, Any]) -> None:
        method = msg.get("method")
        req_id = msg.get("id")
        is_notification = "id" not in msg
        try:
            if method == "initialize":
                self._reply(
                    req_id,
                    {
                        "protocolVersion": PROTOCOL_VERSION,
                        "capabilities": {"tools": {"listChanged": False}},
                        "serverInfo": SERVER_INFO,
                    },
                )
            elif method == "notifications/initialized":
                self.initialized = True
            elif method == "ping":
                self._reply(req_id, {})
            elif method == "tools/list":
                self._reply(req_id, {"tools": [t.spec() for t in TOOLS]})
            elif method == "tools/call":
                self._handle_tool_call(req_id, msg.get("params") or {})
            elif is_notification:
                pass  # unknown notifications are ignored per JSON-RPC
            else:
                self._error(req_id, METHOD_NOT_FOUND, f"unknown method: {method}")
        except Exception as e:  # noqa: BLE001 — protocol loop must survive
            log.exception("error handling %s", method)
            if not is_notification:
                self._error(req_id, INTERNAL_ERROR, str(e))

    def _handle_tool_call(self, req_id: Any, params: dict[str, Any]) -> None:
        name = params.get("name", "")
        tool = TOOLS_BY_NAME.get(name)
        if tool is None:
            self._error(req_id, INVALID_PARAMS, f"unknown tool: {name}")
            return
        args = params.get("arguments") or {}
        missing = [k for k in tool.input_schema.get("required", []) if k not in args]
        if missing:
            self._error(req_id, INVALID_PARAMS, f"missing arguments: {', '.join(missing)}")
            return
        try:
            result = tool.fn(self.ctx, args)
            text = result if isinstance(result, str) else json.dumps(result, ensure_ascii=False)
            self._reply(
                req_id, {"content": [{"type": "text", "text": text}], "isError": False}
            )
        except Exception as e:  # tool failure is a RESULT, not a protocol error
            self._reply(
                req_id,
                {"content": [{"type": "text", "text": f"tool error: {e}"}], "isError": True},
            )

    # -- main loop ---------------------------------------------------------

    def run(self) -> None:
        log.info("MCP stdio server up: %d tools -> %s", len(TOOLS), self.ctx.bridge_url)
        for line in self.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                self._error(None, PARSE_ERROR, "parse error")
                continue
            if not isinstance(msg, dict) or msg.get("jsonrpc") != "2.0":
                self._error(None, INVALID_REQUEST, "invalid request")
                continue
            self.handle_message(msg)
