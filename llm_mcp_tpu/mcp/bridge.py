"""HTTP bridge: thin front door for MCP hosts and simple HTTP clients.

Role parity: reference `mcp/src/index.ts` — a zero-framework `node:http`
server on :3333 that (a) talks gRPC to the core for job submit/get/stream
(`index.ts:90-161`), and (b) reverse-proxies nine plain-HTTP routes to the
core's `/v1/*` surface (`index.ts:163-227`). Here the bridge is Python on the
same stdlib HTTP layer the core uses; gRPC via `GrpcCoreClient` when a gRPC
target is configured, with HTTP fallback so the bridge also works against a
core that only exposes HTTP.
"""

from __future__ import annotations

import logging
from typing import Any
from urllib.parse import urlencode

from ..api.http import HTTPApi, Request, Response
from ..state.queue import JobStatus
from .tools import http_json

log = logging.getLogger("mcp.bridge")

# route-on-the-bridge -> (method, core path); mirrors index.ts:163-227
PROXY_ROUTES: list[tuple[str, str, str]] = [
    ("POST", "/llm/request", "/v1/llm/request"),
    ("GET", "/dashboard", "/v1/dashboard"),
    ("GET", "/costs/summary", "/v1/costs/summary"),
    ("GET", "/costs/balance", "/v1/costs/balance"),
    ("GET", "/benchmarks", "/v1/benchmarks"),
    ("POST", "/discovery/run", "/v1/discovery/run"),
    ("GET", "/models/stats", "/v1/models/stats"),
    ("POST", "/models/sync", "/v1/models/sync"),
    ("POST", "/feedback", "/v1/feedback"),
    ("POST", "/knowledge/ingest", "/v1/knowledge/ingest"),
]


class BridgeServer:
    def __init__(
        self,
        core_http_url: str,
        core_grpc_target: str = "",
        timeout_s: float = 120.0,
    ):
        self.core_http_url = core_http_url.rstrip("/")
        self.timeout_s = timeout_s
        self._grpc = None
        if core_grpc_target:
            try:
                from ..rpc.client import GrpcCoreClient

                self._grpc = GrpcCoreClient(core_grpc_target)
            except Exception as e:  # grpc unavailable: HTTP fallback only
                log.warning("gRPC client unavailable (%s); HTTP-only bridge", e)
        self.api = HTTPApi()
        self._register()
        self._server = None

    # -- plumbing ----------------------------------------------------------

    def _core_request(
        self, method: str, path: str, body: Any = None, timeout: float | None = None
    ) -> tuple[int, Any]:
        return http_json(method, self.core_http_url + path, body, timeout or self.timeout_s)

    def _register(self) -> None:
        r = self.api.route
        r("GET", "/health", self.handle_health)
        r("POST", "/submit", self.handle_submit)
        r("GET", "/jobs/{id}", self.handle_get_job)
        r("GET", "/jobs/{id}/stream", self.handle_stream_job)
        for method, here, there in PROXY_ROUTES:
            r(method, here, self._make_proxy(method, there))

    def _make_proxy(self, method: str, core_path: str):
        def proxy(req: Request, resp: Response) -> None:
            body = None
            if method in ("POST", "PUT"):
                try:
                    body = req.json()
                except Exception:
                    body = {}
            path = core_path
            if req.query:
                path = f"{core_path}?{urlencode(req.query)}"
            status, payload = self._core_request(method, path, body)
            resp.write_json(payload, status=status)

        return proxy

    # -- handlers (index.ts:76-161 parity) ---------------------------------

    def handle_health(self, req: Request, resp: Response) -> None:
        resp.write_json(
            {
                "status": "ok",
                "service": "llm-mcp-tpu-bridge",
                "core": self.core_http_url,
                "grpc": self._grpc is not None,
            }
        )

    def handle_submit(self, req: Request, resp: Response) -> None:
        try:
            body = req.json()
        except Exception:
            resp.write_error("invalid JSON body", 400)
            return
        kind = body.get("kind", "")
        if not kind:
            resp.write_error("kind required", 400)
            return
        payload = body.get("payload", {})
        if self._grpc is not None:
            try:
                job = self._grpc.submit(
                    kind,
                    payload,
                    priority=int(body.get("priority") or 0),
                    # 0 = queue default, matching the HTTP JobsAPI path so the
                    # retry budget doesn't depend on the transport used
                    max_attempts=int(body.get("max_attempts") or 0),
                    deadline_at=float(body.get("deadline_at") or 0.0),
                )
            except (TypeError, ValueError) as e:
                resp.write_error(f"invalid field: {e}", 400)
                return
            except Exception as e:
                status = getattr(e, "status", 502)
                resp.write_error(str(e), status if isinstance(status, int) else 502)
                return
            resp.write_json({"job_id": job["id"], "status": job["status"]}, status=202)
            return
        status, out = self._core_request("POST", "/v1/jobs", body)
        resp.write_json(out, status=status)

    def handle_get_job(self, req: Request, resp: Response) -> None:
        job_id = req.params["id"]
        if self._grpc is not None:
            try:
                resp.write_json(self._grpc.get(job_id))
            except Exception as e:
                status = getattr(e, "status", 502)
                resp.write_error(str(e), status if isinstance(status, int) else 502)
            return
        status, out = self._core_request("GET", f"/v1/jobs/{job_id}")
        resp.write_json(out, status=status)

    def handle_stream_job(self, req: Request, resp: Response) -> None:
        """SSE re-exposure of the job status stream (index.ts:131-161)."""
        job_id = req.params["id"]
        resp.start_sse()
        if self._grpc is not None:
            try:
                for update in self._grpc.stream(job_id, timeout_s=self.timeout_s):
                    if not resp.sse_event("status", update):
                        return
                    if update.get("status") in JobStatus.TERMINAL:
                        break
            except Exception as e:
                resp.sse_event("error", {"error": str(e)})
            return
        # HTTP fallback: poll the core like the reference's polling fallback
        import time

        last = None
        deadline = time.time() + self.timeout_s
        while time.time() < deadline:
            try:
                status, job = self._core_request("GET", f"/v1/jobs/{job_id}")
            except OSError as e:  # core unreachable mid-poll: emit a frame, end
                resp.sse_event("error", {"error": f"core unreachable: {e}"})
                return
            if status != 200:
                msg = "job not found" if status == 404 else f"core error {status}"
                resp.sse_event("error", {"error": msg, "status": status})
                return
            if job.get("status") != last:
                last = job.get("status")
                if not resp.sse_event("status", job):
                    return
            if last in JobStatus.TERMINAL:
                return
            time.sleep(1.0)
        resp.sse_event("timeout", {"error": f"stream timeout after {self.timeout_s}s"})

    # -- lifecycle ---------------------------------------------------------

    def start(self, host: str = "0.0.0.0", port: int = 3333) -> "BridgeServer":
        self._server = self.api.serve(host, port)
        log.info("bridge listening on %s:%s -> %s", host, self.api.port, self.core_http_url)
        return self

    @property
    def port(self) -> int:
        return self.api.port

    def shutdown(self) -> None:
        self.api.shutdown()
        if self._grpc is not None:
            self._grpc.close()
