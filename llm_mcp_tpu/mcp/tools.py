"""The 12 MCP tools, mirroring the reference FastMCP server.

Role parity: reference `fastmcp/server.py` — 12 `@mcp.tool()` wrappers over
the bridge via httpx (`server.py:46-169`). Here each tool is a declarative
spec (name, description, JSON Schema) plus a callable over a `ToolContext`
that performs the HTTP call with stdlib urllib, so the stdio server can
enumerate them for `tools/list` and dispatch `tools/call` without any MCP SDK.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable
from urllib.parse import urlencode


def http_json(method: str, url: str, body: Any = None, timeout: float = 60.0) -> tuple[int, Any]:
    """One JSON request → (status, parsed body); HTTP error statuses are
    returned, not raised (transport failures still raise)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:  # noqa: S310
            return r.status, json.loads(r.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode() or "{}")
        except Exception:
            return e.code, {"error": f"HTTP {e.code}"}


class ToolCallError(RuntimeError):
    """A tool call that reached the bridge but got an HTTP error status."""

    def __init__(self, status: int, body: Any):
        detail = body.get("error") if isinstance(body, dict) else body
        super().__init__(f"HTTP {status}: {json.dumps(detail) if detail else status}")
        self.status = status
        self.body = body


@dataclass
class ToolContext:
    """HTTP access to the bridge (or directly to a core /v1 surface)."""

    bridge_url: str
    timeout_s: float = 60.0

    def request(self, method: str, path: str, body: Any = None, query: dict | None = None) -> Any:
        url = self.bridge_url.rstrip("/") + path
        if query:
            url += "?" + urlencode({k: v for k, v in query.items() if v is not None})
        status, payload = http_json(method, url, body, self.timeout_s)
        if status >= 400:
            # surfaces to the MCP host as an isError=True tool result
            raise ToolCallError(status, payload)
        return payload


@dataclass
class Tool:
    name: str
    description: str
    fn: Callable[[ToolContext, dict[str, Any]], Any]
    input_schema: dict[str, Any] = field(
        default_factory=lambda: {"type": "object", "properties": {}}
    )

    def spec(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "description": self.description,
            "inputSchema": self.input_schema,
        }


def _obj(props: dict[str, Any], required: list[str] | None = None) -> dict[str, Any]:
    schema: dict[str, Any] = {"type": "object", "properties": props}
    if required:
        schema["required"] = required
    return schema


# -- tool implementations (fastmcp/server.py:46-169 parity) -----------------


def _dashboard(ctx: ToolContext, args: dict) -> Any:
    return ctx.request("GET", "/dashboard")


def _submit(ctx: ToolContext, args: dict) -> Any:
    return ctx.request(
        "POST",
        "/submit",
        {
            "kind": args.get("kind", "generate"),
            "payload": args.get("payload", {}),
            "priority": args.get("priority", 0),
            "max_attempts": args.get("max_attempts", 3),
        },
    )


def _job_status(ctx: ToolContext, args: dict) -> Any:
    return ctx.request("GET", f"/jobs/{args['job_id']}")


def _request(ctx: ToolContext, args: dict) -> Any:
    body = {k: v for k, v in args.items() if v is not None}
    return ctx.request("POST", "/llm/request", body)


def _costs(ctx: ToolContext, args: dict) -> Any:
    return ctx.request("GET", "/costs/summary", query={"days": args.get("days")})


def _benchmarks(ctx: ToolContext, args: dict) -> Any:
    return ctx.request("GET", "/benchmarks", query={"limit": args.get("limit")})


def _balance(ctx: ToolContext, args: dict) -> Any:
    return ctx.request("GET", "/costs/balance")


def _model_stats(ctx: ToolContext, args: dict) -> Any:
    return ctx.request("GET", "/models/stats")


def _feedback(ctx: ToolContext, args: dict) -> Any:
    rating = "up" if args.get("positive", True) else "down"
    return ctx.request("POST", "/feedback", {"model": args["model"], "rating": rating})


def _learn(ctx: ToolContext, args: dict) -> Any:
    return ctx.request(
        "POST",
        "/knowledge/ingest",
        {"target": "lightrag", "text": args["text"], "metadata": args.get("metadata", {})},
    )


def _remember(ctx: ToolContext, args: dict) -> Any:
    return ctx.request(
        "POST",
        "/knowledge/ingest",
        {"target": "mem0", "text": args["text"], "user_id": args.get("user_id", "default")},
    )


def _sync_models(ctx: ToolContext, args: dict) -> Any:
    return ctx.request("POST", "/models/sync", {})


TOOLS: list[Tool] = [
    Tool(
        "llm_dashboard",
        "Cluster snapshot: jobs by status, devices, TPU slices, workers, costs, issues.",
        _dashboard,
    ),
    Tool(
        "llm_submit",
        "Submit an async job (generate/embed/benchmark.*/echo) to the durable queue.",
        _submit,
        _obj(
            {
                "kind": {"type": "string", "description": "job kind, e.g. generate"},
                "payload": {"type": "object", "description": "kind-specific payload"},
                "priority": {"type": "integer"},
                "max_attempts": {"type": "integer"},
            },
            ["kind"],
        ),
    ),
    Tool(
        "llm_job_status",
        "Fetch a job by id, including result or error once finished.",
        _job_status,
        _obj({"job_id": {"type": "string"}}, ["job_id"]),
    ),
    Tool(
        "llm_request",
        "Smart-routed LLM request: pick quality tier, route to TPU slice or cloud, enqueue.",
        _request,
        _obj(
            {
                "prompt": {"type": "string"},
                "quality": {
                    "type": "string",
                    "enum": ["turbo", "economy", "standard", "premium", "ultra", "max"],
                },
                "kind": {"type": "string"},
                "model": {"type": "string"},
                "provider": {"type": "string"},
                "thinking": {"type": "boolean"},
            },
            ["prompt"],
        ),
    ),
    Tool("llm_costs", "Cost summary grouped by model/provider.", _costs,
         _obj({"days": {"type": "integer"}})),
    Tool("llm_benchmarks", "Recent benchmark rows (device, model, tps, latency).", _benchmarks,
         _obj({"limit": {"type": "integer"}})),
    Tool("llm_balance", "Live cloud provider credit balance.", _balance),
    Tool("llm_model_stats", "Per-model rolling stats: requests, tokens, cost, success rate.",
         _model_stats),
    Tool(
        "llm_feedback",
        "Thumbs up/down feedback for a model's answer quality.",
        _feedback,
        _obj({"model": {"type": "string"}, "positive": {"type": "boolean"}}, ["model"]),
    ),
    Tool(
        "llm_learn",
        "Ingest text into the LightRAG knowledge base (min 100 chars).",
        _learn,
        _obj({"text": {"type": "string"}, "metadata": {"type": "object"}}, ["text"]),
    ),
    Tool(
        "llm_remember",
        "Store a memory in mem0 (min 10 chars).",
        _remember,
        _obj({"text": {"type": "string"}, "user_id": {"type": "string"}}, ["text"]),
    ),
    Tool("llm_sync_models", "Re-sync the model catalog from engines and cloud providers.",
         _sync_models),
]

TOOLS_BY_NAME: dict[str, Tool] = {t.name: t for t in TOOLS}
