"""MCP layer: HTTP bridge + stdio MCP tool server.

Role parity with the reference's L5:
- `bridge.py`  ≈ `mcp/src/index.ts` — zero-framework HTTP bridge :3333 that
  speaks gRPC to the core for submit/get/stream and reverse-proxies the
  dashboard/cost/etc routes.
- `stdio.py` + `tools.py` ≈ `fastmcp/server.py` — the 12-tool MCP server.
  The reference uses the FastMCP framework; this environment has no MCP SDK,
  so the (small) MCP stdio protocol is implemented directly: JSON-RPC 2.0
  over stdin/stdout with `initialize`, `tools/list`, `tools/call`.
"""

from .bridge import BridgeServer
from .tools import TOOLS, ToolContext
from .stdio import MCPStdioServer

__all__ = ["BridgeServer", "MCPStdioServer", "TOOLS", "ToolContext"]
