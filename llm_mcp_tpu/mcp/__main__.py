"""`python -m llm_mcp_tpu.mcp [bridge|stdio]` — run the MCP layer.

- `bridge` (default): the HTTP bridge service on :3333, the process parity
  of the reference's `llmmcp` compose service (`mcp/src/index.ts`).
- `stdio`: the MCP tool server on stdin/stdout, the parity of
  `fastmcp/server.py` — point an MCP host (Claude Desktop, etc.) at
  `python -m llm_mcp_tpu.mcp stdio`.

Env: CORE_URL (default http://localhost:8080), CORE_GRPC_TARGET (optional),
BRIDGE_ADDR (default :3333), BRIDGE_URL (stdio mode; defaults to CORE-less
bridge URL http://localhost:3333).
"""

from __future__ import annotations

import logging
import os
import sys


def main() -> None:
    mode = sys.argv[1] if len(sys.argv) > 1 else "bridge"
    core_url = os.environ.get("CORE_URL", "http://localhost:8080")

    if mode == "stdio":
        # protocol runs on stdout — logs MUST go to stderr
        logging.basicConfig(stream=sys.stderr, level=os.environ.get("LOG_LEVEL", "INFO"))
        from .stdio import MCPStdioServer
        from .tools import ToolContext

        bridge_url = os.environ.get("BRIDGE_URL", "http://localhost:3333")
        MCPStdioServer(ToolContext(bridge_url)).run()
        return

    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO"),
        format='{"ts":"%(asctime)s","level":"%(levelname)s","logger":"%(name)s","msg":"%(message)s"}',
    )
    from .bridge import BridgeServer

    addr = os.environ.get("BRIDGE_ADDR", ":3333")
    host, _, port = addr.rpartition(":")
    server = BridgeServer(
        core_url, core_grpc_target=os.environ.get("CORE_GRPC_TARGET", "")
    ).start(host or "0.0.0.0", int(port or 3333))
    import signal
    import threading

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.shutdown()


if __name__ == "__main__":
    main()
