"""Pallas TPU attention kernels for the serving hot loop.

The reference streams tokens computed by an external llama.cpp process
(`core/internal/api/handlers.go:2427-2587` proxies Ollama); its hot loop is a
line scanner. Here the hot loop is attention over the KV cache, so it gets
hand-written TPU kernels:

  - `flash_prefill_attention` — causal flash attention for prompt prefill.
    Online-softmax over key blocks: scores never materialize in HBM, VMEM
    holds one [BQ, BK] tile at a time, the two matmuls hit the MXU at
    [128, 128] granularity.
  - `decode_attention` — single-position GQA attention over the cache for
    the continuous batch. Bandwidth-bound: the win is streaming K/V through
    VMEM exactly once per step in their native [S, hd] tiling and fusing
    mask + softmax + weighted sum, with the f32 score tile living only in
    VMEM.

Layout contract (chosen for TPU tiling — (sublane, lane) = trailing dims):

  q (prefill)  [B, H,   S, hd]
  k/v, cache   [B, Hkv, S, hd]     # S×hd trailing → native (8/16, 128) tiles
  q (decode)   [B, Hkv, G, hd]     # G = H // Hkv query heads per KV head
  lengths      [B] int32           # valid positions per slot/row

This is why the engine cache is [L, B, Hkv, S, hd] (heads BEFORE sequence):
a [.., S, 1, hd] block would tile as (1, 128) sublane-padded 8×, wasting
most of the HBM bandwidth the decode step is bound by.

Both kernels auto-fall back to interpret mode off-TPU so the full test suite
exercises them on the CPU backend (tests/conftest.py forces JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on some CPU-only builds; interpret mode needs pl only
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _smem_spec() -> pl.BlockSpec:
    """Whole-array spec for the [B] lengths input: SMEM on TPU (scalar reads
    drive masking), memory-space-agnostic under interpret mode off-TPU."""
    if _HAS_PLTPU:
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec(memory_space=pl.ANY)  # pragma: no cover

NEG_INF = float(-1e30)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def pallas_supported(seq_len: int, head_dim: int) -> bool:
    """Static (trace-time) eligibility for the Pallas path."""
    if os.environ.get("LLM_MCP_TPU_ATTN", "auto") == "xla":
        return False
    if head_dim % 128 != 0 and head_dim not in (32, 64):
        return False
    if seq_len >= 128:
        return seq_len % 128 == 0
    return seq_len & (seq_len - 1) == 0  # pow2 buckets below one block


def resolve_attn_impl(mesh=None) -> str:
    """Pick the attention implementation at trace time.

    env LLM_MCP_TPU_ATTN: auto (default) | pallas | xla.
    auto → pallas on a single TPU chip, xla elsewhere: sharded meshes keep
    the einsum path (GSPMD partitions it) until the shard_map kernel wrap
    lands alongside the ring-attention long-context path. CPU tests exercise
    the kernels in interpret mode by passing attn_impl="pallas" /
    LLM_MCP_TPU_ATTN=pallas explicitly — see tests/test_kernels.py.
    """
    if mesh is not None and mesh.size > 1:
        # Sharded mesh: the unwrapped pallas_call must not trace over GSPMD
        # inputs, even when LLM_MCP_TPU_ATTN=pallas is set.
        return "xla"
    mode = os.environ.get("LLM_MCP_TPU_ATTN", "auto")
    if mode in ("pallas", "xla"):
        return mode
    return "pallas" if _on_tpu() else "xla"


def decode_pallas_max_seq(
    head_dim: int, n_kv_heads: int, n_heads: int, quantized: bool
) -> int:
    """Longest cache row the whole-S decode kernels can stream through VMEM.

    Both whole-S decode arms load a full [.., S, hd] K/V tile per grid cell
    (plus f32 score/prob tiles), double-buffered by the pipeline. Beyond
    this cap a whole-S pallas_call would fail AT RUNTIME on a real chip
    with a VMEM allocation error — `decode_attend_q8`/`decode_attend_bf16`
    must pick their BLOCKED arm statically instead (VERDICT r1 #8: nothing
    enforced the boundary).

      q8 kernel (one cell = one batch row, all KV heads; the fused-layout
      BlockSpec reads only the 2·Hkv payload heads, never the packed
      scale row):
        2 × 2·Hkv·hd int8 payload (k+v fused, double-buffered)
        + 2·Hkv scale bytes + 2 × H f32 score/prob rows   per cache position
      bf16 kernel (one cell = one (row, head)):
        2 × hd·2 bf16 payload (k+v, double-buffered) + G·4 scores
    """
    budget = 12 * 1024 * 1024  # of ~16 MB VMEM; headroom for q/out/temps
    if quantized:
        per_pos = 2 * (2 * n_kv_heads * head_dim) + 8 * n_kv_heads + 2 * 4 * n_heads
    else:
        g = max(1, n_heads // n_kv_heads)
        per_pos = 2 * (2 * head_dim * 2) + 4 * g
    return max(128, budget // per_pos)


def resolve_decode_impl(
    mesh=None,
    quantized: bool = False,
    *,
    seq_len: int = 0,
    head_dim: int = 128,
    n_kv_heads: int = 8,
    n_heads: int = 32,
) -> str:
    """Attention impl for the DECODE step (prefill keeps resolve_attn_impl).

    For the INT8 cache the default on TPU is the `decode_attend_q8` Pallas
    kernel: XLA's int8 einsum path materializes a bf16 copy of the
    dequantized cache (measured 236 GB/s effective at 8B B=64 — slower than
    the bf16 cache), while the kernel streams the int8 payload into s8 MXU
    dots with no bulk converts.

    The bf16 cache now defaults to Pallas on a single TPU chip too:
    `decode_attend_bf16` runs the same scan-invariant-cache + post-scan
    batched-append structure as the q8 path (the structure that made q8
    fast), with a runtime whole-S/blocked hybrid. The old in-scan sliced
    kernel this resolver used to reject in favor of XLA (measured 10.4 vs
    6.2 ms/step at B=32) is gone from the decode routing. There is no seq
    cap either way anymore: past `decode_pallas_max_seq` both dtypes pick
    their blocked arm statically (HBM streaming, no VMEM cliff).
    env LLM_MCP_TPU_ATTN still forces either path for tests; the
    `head_dim`/`n_kv_heads`/`n_heads`/`seq_len` kwargs stay for callers
    and tests probing the VMEM budget."""
    del seq_len, head_dim, n_kv_heads, n_heads  # cap moved into the hybrids
    if mesh is not None and mesh.size > 1:
        # Same rule as resolve_attn_impl: the unwrapped pallas_call must not
        # trace over GSPMD-sharded cache operands (the einsum path partitions
        # cleanly; the q8 kernel would force replication or fail to compile).
        return "xla"
    mode = os.environ.get("LLM_MCP_TPU_ATTN", "auto")
    if mode in ("pallas", "xla"):
        return mode
    del quantized  # both cache dtypes default to the pallas hybrids on-chip
    return "pallas" if _on_tpu() else "xla"


def _interpret() -> bool:
    return not _on_tpu()


# ---------------------------------------------------------------------------
# Prefill: causal flash attention
# ---------------------------------------------------------------------------


def _flash_prefill_kernel(
    lengths_ref,  # [B] int32 (SMEM)
    window_ref,  # [1] int32 (SMEM) — sliding window, 0 = global
    q_ref,  # [1, 1, BQ, hd]
    k_ref,  # [1, 1, S, hd]
    v_ref,  # [1, 1, S, hd]
    o_ref,  # [1, 1, BQ, hd]
    *,
    scale: float,
    block_k: int,
    seq_len: int,
    softcap: float,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    bq = q_ref.shape[2]
    hd = q_ref.shape[3]
    valid_len = lengths_ref[b]
    window = window_ref[0]

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [BQ, hd]
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)  # [BQ, 1]

    acc = jnp.zeros((bq, hd), dtype=jnp.float32)
    m = jnp.full((bq, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((bq, 1), dtype=jnp.float32)

    # Causal: key block kb only matters while kb*BK <= last q position.
    n_kb = jnp.minimum((qi * bq + bq + block_k - 1) // block_k, seq_len // block_k)

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        if softcap:
            s = jnp.tanh(s / softcap) * softcap
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )  # [1, BK]
        mask = (k_pos <= q_pos) & (k_pos < valid_len)
        mask &= (window == 0) | (q_pos - k_pos < window)
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # Mask p explicitly: a fully-masked row keeps m_new == NEG_INF, where
        # exp(s - m_new) == 1 would silently average V; masked p keeps l == 0
        # so the guard below emits 0 for such rows.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l

    acc, m, l = jax.lax.fori_loop(0, n_kb, body, (acc, m, l))
    # l == 0 when a row saw no unmasked key (valid_len == 0) — emit 0
    # instead of 0/0 NaN. Padding rows with valid_len > 0 still attend the
    # valid prefix and produce garbage the caller never reads.
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret", "softcap", "scale")
)
def flash_prefill_attention(
    q: jnp.ndarray,  # [B, H, S, hd]
    k: jnp.ndarray,  # [B, Hkv, S, hd]
    v: jnp.ndarray,  # [B, Hkv, S, hd]
    lengths: jnp.ndarray,  # [B] int32
    *,
    window: jnp.ndarray | int = 0,  # sliding window (0 = global); may be traced
    softcap: float = 0.0,  # Gemma2-style score soft-capping (0 = off)
    scale: float = 0.0,  # query scale override (0 = head_dim**-0.5)
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Causal + length-masked GQA flash attention. Returns [B, H, S, hd]."""
    B, H, S, hd = q.shape
    Hkv = k.shape[1]
    G = H // Hkv
    bq = min(block_q, S)
    bk = min(block_k, S)
    assert S % bq == 0 and S % bk == 0, (S, bq, bk)
    interp = _interpret() if interpret is None else interpret

    kernel = functools.partial(
        _flash_prefill_kernel,
        scale=scale or hd**-0.5,
        block_k=bk,
        seq_len=S,
        softcap=softcap,
    )
    win = jnp.reshape(jnp.asarray(window, dtype=jnp.int32), (1,))
    return pl.pallas_call(
        kernel,
        grid=(B, H, S // bq),
        in_specs=[
            _smem_spec(),  # lengths [B]
            _smem_spec(),  # window [1]
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, qi: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, S, hd), lambda b, h, qi: (b, h // G, 0, 0)),
            pl.BlockSpec((1, 1, S, hd), lambda b, h, qi: (b, h // G, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, hd), lambda b, h, qi: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        interpret=interp,
    )(lengths.astype(jnp.int32), win, q, k, v)


# ---------------------------------------------------------------------------
# Decode: one-position GQA attention over the KV cache
# ---------------------------------------------------------------------------


def _decode_attn_kernel(
    lengths_ref,  # [B] int32 (scalar prefetch)
    q_ref,  # [1, 1, G, hd]
    k_ref,  # [1, 1, S, hd]
    v_ref,  # [1, 1, S, hd]
    o_ref,  # [1, 1, G, hd]
    *,
    scale: float,
):
    b = pl.program_id(0)
    valid_len = lengths_ref[b]  # attend to positions 0..valid_len inclusive
    S = k_ref.shape[2]

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [G, hd]
    k = k_ref[0, 0].astype(jnp.float32)  # [S, hd]
    v = v_ref[0, 0].astype(jnp.float32)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [G, S]
    pos = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
    s = jnp.where(pos <= valid_len, s, NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    ctx = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [G, hd]
    o_ref[0, 0] = (ctx / l).astype(o_ref.dtype)


def _attend_q8_kernel(
    li_ref,  # [1] int32 (scalar prefetch) — layer index
    ids_ref,  # [Ba] int32 (scalar prefetch) — cache row per batch position
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    q_ref,  # [1, Hkv, G, hd]
    nk_ref,  # [1, Hkv, 1, hd] — this step's K vectors (post-rope)
    nv_ref,  # [1, Hkv, 1, hd]
    kv_ref,  # [1, 1, 2*Hkv, S, hd] int8 — fused K|V payload, all heads
    s_ref,  # [1, 1, 2*Hkv, S] — fused K|V dequant scales
    o_ref,  # [1, Hkv, G, hd] — attention output
    *,
    scale: float,
):
    """One grid cell = one batch row, all KV heads.

    The cache rides the FUSED layout (models/llama.py:init_kv_cache): K
    heads [0, Hkv), V heads [Hkv, 2*Hkv) of one int8 payload array, so the
    pipeline issues ONE payload DMA + one scales DMA per cell instead of
    four. The padded packed-scale pseudo-head (head 2*Hkv, blocked-kernel
    fuel) is excluded by the BlockSpec — this kernel reads the plain "s"
    rows.

    Perf-critical invariant: the int8 K/V payloads feed the MXU *as int8*
    (s8 x s8 -> s32 dots). Converting them elementwise would bottleneck on
    the VPU — int8->f32 converts run at ~1 elem/lane/cycle, about the same
    rate HBM delivers bytes, doubling step time. Only the tiny per-row
    tensors (q, scores, probs) are computed in f32.
    """
    b = pl.program_id(0)
    w = lengths_ref[b]  # this step's position; attend to 0..w inclusive
    S = kv_ref.shape[3]
    Hkv = q_ref.shape[1]
    G = q_ref.shape[2]

    nk = nk_ref[0, :, 0].astype(jnp.float32)  # [Hkv, hd]
    nv = nv_ref[0, :, 0].astype(jnp.float32)
    q = q_ref[0].astype(jnp.float32)  # [Hkv, G, hd]
    ss = s_ref[0, 0].astype(jnp.float32)  # [2*Hkv, S]
    kss, vss = ss[:Hkv], ss[Hkv:]

    # quantize q per (h, g) row; fold the attention scale into the q scales
    qa = jnp.max(jnp.abs(q), axis=-1)  # [Hkv, G]
    qsc = jnp.maximum(qa / 127.0, 1e-30)
    q8 = jnp.round(q / qsc[..., None]).astype(jnp.int8)

    kvq = kv_ref[0, 0]  # [2*Hkv, S, hd] int8 — k rows then v rows
    s_i = jax.lax.dot_general(
        q8,
        kvq[:Hkv],
        (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.int32,
    )  # [Hkv, G, S]
    s = s_i.astype(jnp.float32) * (scale * qsc)[..., None] * kss[:, None, :]

    pos = jax.lax.broadcasted_iota(jnp.int32, (1, 1, S), 2)
    # the tile holds the PRE-append cache — position w's score/value come
    # from the unquantized new vectors instead (exact; the quantized row
    # scatters into the cache outside the kernel)
    s_new = jnp.sum(q * nk[:, None, :], axis=-1, keepdims=True) * scale  # [Hkv, G, 1]
    s = jnp.where(pos == w, s_new, s)
    s = jnp.where(pos <= w, s, NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p_w = jnp.sum(jnp.where(pos == w, p, 0.0), axis=-1, keepdims=True)  # [Hkv, G, 1]
    # fold v's dequant scales into the probs, then quantize the prob rows so
    # the PV dot also runs s8 x s8 on the MXU
    pv = jnp.where(pos == w, 0.0, p * vss[:, None, :])  # [Hkv, G, S]
    pa = jnp.max(pv, axis=-1)  # [Hkv, G]
    psc = jnp.maximum(pa / 127.0, 1e-30)
    p8 = jnp.round(pv / psc[..., None]).astype(jnp.int8)
    ctx_i = jax.lax.dot_general(
        p8,
        kvq[Hkv:],
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.int32,
    )  # [Hkv, G, hd]
    ctx = ctx_i.astype(jnp.float32) * psc[..., None] + p_w * nv[:, None, :]
    o_ref[0] = (ctx / l).astype(o_ref.dtype)


def _unpack_scale_lanes(srow, n_heads: int, scale_dtype):
    """In-kernel inverse of models/quant.py:pack_scales for one landed
    block: [BS, hd] int8 scale-row bytes -> [n_heads, BS] scales. Byte
    layout parity with pack_scales is pinned by the fused-layout parity
    tests (a drifting layout would desync every dequant)."""
    it = jnp.dtype(scale_dtype).itemsize
    raw = srow[:, : n_heads * it].reshape(srow.shape[0], n_heads, it)
    s = jax.lax.bitcast_convert_type(raw, scale_dtype)  # [BS, n_heads]
    return jnp.swapaxes(s, 0, 1)


def _attend_q8_blocked_kernel(
    li_ref,  # [1] int32 (scalar prefetch) — layer index
    ids_ref,  # [Ba] int32 (scalar prefetch) — cache row per batch position
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    q_ref,  # [1, Hkv, G, hd] VMEM
    nk_ref,  # [1, Hkv, 1, hd] VMEM — this step's K vectors (post-rope)
    nv_ref,  # [1, Hkv, 1, hd] VMEM
    pay_hbm,  # [L, B, 2*Hkv + p, S, hd] int8 — fused K|V(|packed scales)
    #           payload, stays in HBM (ANY), DMA'd per block
    s_hbm,  # [L, B, 2*Hkv, S] — plain scales (read only when packed=False)
    o_ref,  # [1, Hkv, G, hd] VMEM out
    pay_buf,  # VMEM scratch [2, Hh, BS, hd] int8 (double buffer);
    #           Hh = 2*Hkv + 1 when packed else 2*Hkv
    s_buf,  # [2, 2*Hkv, BS] (unused when packed — tiny, kept so both modes
    #        share one scratch list)
    sems,  # DMA semaphores [2, 2]
    *,
    scale: float,
    block_s: int,
    seq_len: int,
    packed: bool,
    scale_dtype,
):
    """Dynamic-length decode attention: only the cache blocks that contain
    attended positions ([0, w]) ever leave HBM.

    The whole-S kernel's BlockSpec DMAs the full row regardless of how much
    of it is valid — at S=1024 with half-full slots that's 2x the necessary
    cache traffic, and decode is cache-bandwidth-bound. Here the row stays
    in HBM (memory_space=ANY) and a manual double-buffered DMA loop with a
    DYNAMIC trip count (ceil((w+1)/BS)) streams exactly the attended prefix,
    flash-style online softmax accumulating across blocks. Same s8-MXU dot
    discipline and exact current-position override as `_attend_q8_kernel`.

    DMA count per (row, block) cell is the r05-measured bottleneck
    (~2.5 µs of issue latency per cell regardless of bytes): the fused
    layout collapses the old 4 copies (kq/ks/vq/vs) to

      packed=True  — ONE copy: K, V and a bit-packed per-position scale
        pseudo-head travel in the same [2*Hkv+1, BS, hd] int8 block; the
        scales are unpacked in VMEM (`_unpack_scale_lanes`).
      packed=False — TWO copies: the [2*Hkv, BS, hd] payload head-slice
        plus one [2*Hkv, BS] block of the plain scales array. This is the
        fallback when the scale bytes don't fit one head row
        (2*Hkv*itemsize > hd) or LLM_MCP_TPU_Q8_SCALE_PACK=0. Unlike the
        r05-rejected per-cache single-row [2, BS] loads, a [2*Hkv, BS]
        slice of the head-major scales array is a (sublane, lane)-tileable
        copy Mosaic accepts.
    """
    b = pl.program_id(0)
    li = li_ref[0]
    row = ids_ref[b]  # cache row for this batch position (compaction)
    w = lengths_ref[b]
    BS = block_s
    Hkv = q_ref.shape[1]
    nblk_max = seq_len // BS
    nblk = jnp.clip((w + BS) // BS, 1, nblk_max)
    # parked/free rows (w >= S, engine convention) produce discarded output:
    # stream one block instead of the whole row — at low occupancy most of
    # the batch is parked and would otherwise dominate cache traffic
    nblk = jnp.where(w >= seq_len, 1, nblk)

    def copies(j, slot):
        if packed:
            # one DMA: full head axis (K | V | packed-scale pseudo-head)
            return (
                pltpu.make_async_copy(
                    pay_hbm.at[li, row, :, pl.ds(j * BS, BS), :],
                    pay_buf.at[slot],
                    sems.at[slot, 0],
                ),
            )
        return (
            pltpu.make_async_copy(
                pay_hbm.at[li, row, pl.ds(0, 2 * Hkv), pl.ds(j * BS, BS), :],
                pay_buf.at[slot],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                s_hbm.at[li, row, :, pl.ds(j * BS, BS)],
                s_buf.at[slot],
                sems.at[slot, 1],
            ),
        )

    def start(j, slot):
        for c in copies(j, slot):
            c.start()

    def wait(j, slot):
        for c in copies(j, slot):
            c.wait()

    start(0, 0)

    q = q_ref[0].astype(jnp.float32)  # [Hkv, G, hd]
    nk = nk_ref[0, :, 0].astype(jnp.float32)  # [Hkv, hd]
    nv = nv_ref[0, :, 0].astype(jnp.float32)
    qa = jnp.max(jnp.abs(q), axis=-1)
    qsc = jnp.maximum(qa / 127.0, 1e-30)
    q8 = jnp.round(q / qsc[..., None]).astype(jnp.int8)
    s_new = jnp.sum(q * nk[:, None, :], axis=-1, keepdims=True) * scale  # [Hkv,G,1]

    G = q_ref.shape[2]
    hd = q_ref.shape[3]
    acc0 = jnp.zeros((Hkv, G, hd), jnp.float32)
    m0 = jnp.full((Hkv, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nblk)
        def _prefetch():
            start(j + 1, 1 - slot)

        wait(j, slot)
        buf = pay_buf[slot]  # [Hh, BS, hd] int8 — k rows, v rows(, scales)
        k = buf[:Hkv]  # [Hkv, BS, hd] int8
        if packed:
            ss = _unpack_scale_lanes(buf[2 * Hkv], 2 * Hkv, scale_dtype)
        else:
            ss = s_buf[slot]
        ss = ss.astype(jnp.float32)  # [2*Hkv, BS]
        kss, vss = ss[:Hkv], ss[Hkv:]
        s_i = jax.lax.dot_general(
            q8, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.int32
        )  # [Hkv, G, BS]
        s = s_i.astype(jnp.float32) * (scale * qsc)[..., None] * kss[:, None, :]
        pos = j * BS + jax.lax.broadcasted_iota(jnp.int32, (1, 1, BS), 2)
        s = jnp.where(pos == w, s_new, s)
        s = jnp.where(pos <= w, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(pos <= w, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        p_w = jnp.sum(jnp.where(pos == w, p, 0.0), axis=-1, keepdims=True)
        pv = jnp.where(pos == w, 0.0, p * vss[:, None, :])
        pa = jnp.max(pv, axis=-1)
        psc = jnp.maximum(pa / 127.0, 1e-30)
        p8 = jnp.round(pv / psc[..., None]).astype(jnp.int8)
        ctx_i = jax.lax.dot_general(
            p8,
            buf[Hkv : 2 * Hkv],
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32,
        )  # [Hkv, G, hd]
        acc_new = (
            acc * alpha + ctx_i.astype(jnp.float32) * psc[..., None] + p_w * nv[:, None, :]
        )
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, nblk, body, (acc0, m0, l0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _attend_q8_paged_kernel(
    li_ref,  # [1] int32 (scalar prefetch) — layer index
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    tbl_ref,  # [Ba * nbs] int32 (scalar prefetch) — flattened per-row block
    #          tables: physical block id per logical block (already gathered
    #          to the compact batch; arena homes < pool_base, pool rows >=)
    q_ref,  # [1, Hkv, G, hd] VMEM
    nk_ref,  # [1, Hkv, 1, hd] VMEM
    nv_ref,  # [1, Hkv, 1, hd] VMEM
    pay_hbm,  # [L, B, 2*Hkv + p, S, hd] int8 — slot arena (identity homes)
    s_hbm,  # [L, B, 2*Hkv, S] — arena plain scales (packed=False only)
    pool_pay_hbm,  # [L, PXB, 2*Hkv + p, bt, hd] int8 — prefix block pool
    pool_s_hbm,  # [L, PXB, 2*Hkv, bt] — pool plain scales
    o_ref,  # [1, Hkv, G, hd] VMEM out
    pay_buf,  # VMEM scratch [2, Hh, BS, hd] int8 (double buffer)
    s_buf,  # [2, 2*Hkv, BS]
    sems,  # DMA semaphores [2, 2]
    *,
    scale: float,
    block_s: int,
    seq_len: int,
    packed: bool,
    scale_dtype,
):
    """Block-indirect sibling of `_attend_q8_blocked_kernel` (vLLM
    PagedAttention, Kwon et al. 2023): identical math and double-buffered
    streaming, but each block's DMA source resolves through the per-row
    block table instead of a contiguous S-range. BS equals the ledger's
    block_tokens, so logical block j covers exactly table entry j.

    The one-DMA-per-cell property survives the indirection: per block the
    kernel still issues one packed copy (or two unpacked) — the table adds
    a scalar-prefetch lookup and a two-way `pl.when` on the source array
    (arena home vs. pool row), not extra copies. Both branches land the
    same block shape in the same scratch buffer, so wait() reconstructs
    the matching descriptor under the same branch."""
    b = pl.program_id(0)
    li = li_ref[0]
    w = lengths_ref[b]
    BS = block_s
    Hkv = q_ref.shape[1]
    nbs = seq_len // BS
    pool_base = pay_hbm.shape[1] * nbs
    nblk = jnp.clip((w + BS) // BS, 1, nbs)
    # parked/free rows (w >= S, engine convention) stream one block; their
    # table rows are identity (reset on free), so the lookup is always safe
    nblk = jnp.where(w >= seq_len, 1, nblk)

    def arena_copies(phys, slot):
        arow = phys // nbs
        aoff = (phys % nbs) * BS
        if packed:
            return (
                pltpu.make_async_copy(
                    pay_hbm.at[li, arow, :, pl.ds(aoff, BS), :],
                    pay_buf.at[slot],
                    sems.at[slot, 0],
                ),
            )
        return (
            pltpu.make_async_copy(
                pay_hbm.at[li, arow, pl.ds(0, 2 * Hkv), pl.ds(aoff, BS), :],
                pay_buf.at[slot],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                s_hbm.at[li, arow, :, pl.ds(aoff, BS)],
                s_buf.at[slot],
                sems.at[slot, 1],
            ),
        )

    def pool_copies(phys, slot):
        prow = phys - pool_base
        if packed:
            return (
                pltpu.make_async_copy(
                    pool_pay_hbm.at[li, prow], pay_buf.at[slot], sems.at[slot, 0]
                ),
            )
        return (
            pltpu.make_async_copy(
                pool_pay_hbm.at[li, prow, pl.ds(0, 2 * Hkv)],
                pay_buf.at[slot],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                pool_s_hbm.at[li, prow], s_buf.at[slot], sems.at[slot, 1]
            ),
        )

    def issue(j, slot, op):
        phys = tbl_ref[b * nbs + j]
        ina = phys < pool_base

        @pl.when(ina)
        def _arena():
            for c in arena_copies(phys, slot):
                getattr(c, op)()

        @pl.when(jnp.logical_not(ina))
        def _pool():
            for c in pool_copies(phys, slot):
                getattr(c, op)()

    issue(0, 0, "start")

    q = q_ref[0].astype(jnp.float32)  # [Hkv, G, hd]
    nk = nk_ref[0, :, 0].astype(jnp.float32)  # [Hkv, hd]
    nv = nv_ref[0, :, 0].astype(jnp.float32)
    qa = jnp.max(jnp.abs(q), axis=-1)
    qsc = jnp.maximum(qa / 127.0, 1e-30)
    q8 = jnp.round(q / qsc[..., None]).astype(jnp.int8)
    s_new = jnp.sum(q * nk[:, None, :], axis=-1, keepdims=True) * scale  # [Hkv,G,1]

    G = q_ref.shape[2]
    hd = q_ref.shape[3]
    acc0 = jnp.zeros((Hkv, G, hd), jnp.float32)
    m0 = jnp.full((Hkv, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nblk)
        def _prefetch():
            issue(j + 1, 1 - slot, "start")

        issue(j, slot, "wait")
        buf = pay_buf[slot]  # [Hh, BS, hd] int8 — k rows, v rows(, scales)
        k = buf[:Hkv]  # [Hkv, BS, hd] int8
        if packed:
            ss = _unpack_scale_lanes(buf[2 * Hkv], 2 * Hkv, scale_dtype)
        else:
            ss = s_buf[slot]
        ss = ss.astype(jnp.float32)  # [2*Hkv, BS]
        kss, vss = ss[:Hkv], ss[Hkv:]
        s_i = jax.lax.dot_general(
            q8, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.int32
        )  # [Hkv, G, BS]
        s = s_i.astype(jnp.float32) * (scale * qsc)[..., None] * kss[:, None, :]
        pos = j * BS + jax.lax.broadcasted_iota(jnp.int32, (1, 1, BS), 2)
        s = jnp.where(pos == w, s_new, s)
        s = jnp.where(pos <= w, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(pos <= w, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        p_w = jnp.sum(jnp.where(pos == w, p, 0.0), axis=-1, keepdims=True)
        pv = jnp.where(pos == w, 0.0, p * vss[:, None, :])
        pa = jnp.max(pv, axis=-1)
        psc = jnp.maximum(pa / 127.0, 1e-30)
        p8 = jnp.round(pv / psc[..., None]).astype(jnp.int8)
        ctx_i = jax.lax.dot_general(
            p8,
            buf[Hkv : 2 * Hkv],
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32,
        )  # [Hkv, G, hd]
        acc_new = (
            acc * alpha + ctx_i.astype(jnp.float32) * psc[..., None] + p_w * nv[:, None, :]
        )
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, nblk, body, (acc0, m0, l0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def paged_gather(arena, pool, tables, *, nbs=None):
    """XLA block-indirect gather: materialize contiguous-equivalent rows by
    resolving each logical block through the table — the read-side twin of
    the paged Pallas kernels for every XLA path (CPU serve, chunked-prefill
    past reads, exact fallbacks, multi-layer snapshot reads).

    arena  [B, Hx, S, *rest]   layer-selected slot arena (identity homes)
    pool   [PXB, Hx, bt, *rest] layer-selected prefix pool
    tables [A, nsel] int32     per-row block tables (compact batch); a
        PREFIX of the full table may be passed (chunked prefill gathers
        only the blocks covering its static `skey` bound) — then `nbs`
        must name the full blocks-per-slot so physical ids decode right
    returns [A, Hx, nsel*bt, *rest] rows as the contiguous layout holds them

    Works shape-generically over trailing dims (absent for int8 scale
    planes). Cost is one advanced-indexing gather per source plus a
    `jnp.where` — no full-arena copy beyond the [A, Hx, nsel*bt] result
    itself, which is exactly what the contiguous row-select produced."""
    B, Hx, S = arena.shape[0], arena.shape[1], arena.shape[2]
    rest = arena.shape[3:]
    A, nsel = tables.shape
    nbs = nsel if nbs is None else nbs
    bt = S // nbs
    pool_base = B * nbs
    blk = arena.reshape(B, Hx, nbs, bt, *rest)
    safe = jnp.clip(tables, 0, pool_base - 1)
    # advanced indices at axes 0 and 2 (separated by a slice) land in front:
    # [A, nbs, Hx, bt, *rest]
    arena_take = blk[safe // nbs, :, safe % nbs]
    pidx = jnp.clip(tables - pool_base, 0, max(pool.shape[0] - 1, 0))
    pool_take = pool[pidx]  # [A, nbs, Hx, bt, *rest]
    ina = (tables < pool_base).reshape(A, nsel, *([1] * (arena_take.ndim - 2)))
    g = jnp.where(ina, arena_take, pool_take)
    return jnp.swapaxes(g, 1, 2).reshape(A, Hx, nsel * bt, *rest)


def fused_q8_heads(cache_k: dict) -> tuple[int, int]:
    """(Hkv, p) of a FUSED int8 GQA cache: the payload carries 2*Hkv K|V
    heads plus p ∈ {0, 1} packed-scale pseudo-heads; the plain "s" array
    always has exactly 2*Hkv."""
    Hs = cache_k["s"].shape[2]
    return Hs // 2, cache_k["q"].shape[2] - Hs


def _decode_attend_q8_fallback(
    q, new_k, new_v, cache_k, cache_v, layer, lengths, sc, slot_ids=None,
    block_tables=None, pool=None,
):
    """Exact-f32 mirror of the q8 kernels' math (no q/prob requant) over the
    FUSED cache layout. Used on CPU builds without pallas-tpu and for cache
    lengths no int8-tileable block size divides. `cache_v` is the fused
    layout's empty-dict placeholder (V lives in cache_k's head axis). With
    `block_tables`/`pool` the rows are block-indirect-gathered first
    (`paged_gather`), so this is also the exact reference for the paged
    kernels and the CPU serve path under physical paging."""
    del cache_v
    S = cache_k["q"].shape[3]
    Hkv, _ = fused_q8_heads(cache_k)
    pay = jax.lax.dynamic_index_in_dim(cache_k["q"], layer, 0, keepdims=False)
    ss = jax.lax.dynamic_index_in_dim(cache_k["s"], layer, 0, keepdims=False)
    if block_tables is not None:
        tbl = (
            block_tables
            if slot_ids is None
            else jnp.take(block_tables, slot_ids, 0)
        )
        pp = jax.lax.dynamic_index_in_dim(pool["q"], layer, 0, keepdims=False)
        ps = jax.lax.dynamic_index_in_dim(pool["s"], layer, 0, keepdims=False)
        pay = paged_gather(pay, pp, tbl)
        ss = paged_gather(ss, ps, tbl)
    elif slot_ids is not None:
        pay = jnp.take(pay, slot_ids, 0)
        ss = jnp.take(ss, slot_ids, 0)
    kf, vf = pay[:, :Hkv], pay[:, Hkv : 2 * Hkv]
    kss, vss = ss[:, :Hkv], ss[:, Hkv:]
    qf = q.astype(jnp.float32) * sc
    s = jnp.einsum("bhgd,bhsd->bhgs", qf, kf.astype(jnp.float32)) * kss.astype(
        jnp.float32
    )[:, :, None, :]
    pos = jnp.arange(S)[None, None, None, :]
    w = lengths[:, None, None, None]
    s_new = jnp.einsum("bhgd,bhd->bhg", qf, new_k.astype(jnp.float32))
    s = jnp.where(pos == w, s_new[..., None], s)
    s = jnp.where(pos <= w, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p_w = jnp.sum(jnp.where(pos == w, p, 0.0), axis=-1)  # [B, Hkv, G]
    pv = jnp.where(pos == w, 0.0, p * vss.astype(jnp.float32)[:, :, None, :])
    ctx = jnp.einsum("bhgs,bhsd->bhgd", pv, vf.astype(jnp.float32))
    ctx = ctx + p_w[..., None] * new_v.astype(jnp.float32)[:, :, None, :]
    return ctx.astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "scale"))
def decode_attend_q8(
    q: jnp.ndarray,  # [Ba, Hkv, G, hd] — COMPACT batch (active rows only)
    new_k: jnp.ndarray,  # [Ba, Hkv, hd] — post-rope K for this step
    new_v: jnp.ndarray,  # [Ba, Hkv, hd]
    cache_k: dict,  # FUSED: {"q": int8 [L,B,2*Hkv+p,S,hd], "s": [L,B,2*Hkv,S]}
    cache_v: dict,  # {} — V rides cache_k's head axis (layout invariant)
    layer: jnp.ndarray,  # scalar int32
    lengths: jnp.ndarray,  # [Ba] int32 — this step's position per row
    *,
    slot_ids: jnp.ndarray | None = None,  # [Ba] int32 cache rows (None = 1:1)
    block_tables: jnp.ndarray | None = None,  # [n_slots, nbs] int32 physical
    #   block tables (executor/physical.py); None = contiguous layout
    pool_k: dict | None = None,  # prefix pool mirroring cache_k's structure:
    #   {"q": int8 [L,PXB,2*Hkv+p,bt,hd], "s": [L,PXB,2*Hkv,bt]}
    scale: float = 0.0,  # query scale (0 = head_dim**-0.5)
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Attention over the FUSED int8 KV cache for one layer of the decode
    step (layout: models/llama.py:init_kv_cache — K heads, V heads, and an
    optional bit-packed scale pseudo-head share one payload array, PRE-
    append).

    With `block_tables`/`pool_k` the cache is block-indirect: a runtime
    identity check keeps the exact contiguous dispatch (including the
    whole-S/blocked hybrid) whenever no row references a shared block —
    raw decode without prefix sharing pays one `jnp.all` on a tiny int32
    table, not a gather — and otherwise streams through
    `_attend_q8_paged_kernel`. `LLM_MCP_TPU_Q8_DECODE=paged` forces the
    paged arm (parity tests).

    The int8 payload streams from HBM straight into s8 x s8 -> s32 MXU dots
    (XLA's einsum path materializes a dequantized bf16 copy and runs ~2x
    slower than the bf16 cache); per-token dequant scales fold in post-dot.
    The caller owns the cache append (single-row write-back blocks would
    violate TPU (8, 128) block alignment): whether the row at `lengths[b]`
    has been scattered yet or not, the kernel overrides that position's
    score/value with the exact `new_k`/`new_v` vectors, so the appended
    token is always attended at full precision.

    Returns ctx [B, Hkv, G, hd].
    """
    B, Hkv, G, hd = q.shape
    S = cache_k["q"].shape[3]
    interp = _interpret() if interpret is None else interpret
    sc = scale or hd**-0.5
    _, p = fused_q8_heads(cache_k)

    if not _HAS_PLTPU:  # pragma: no cover — CPU builds without pallas-tpu
        return _decode_attend_q8_fallback(
            q, new_k, new_v, cache_k, cache_v, layer, lengths, sc, slot_ids,
            block_tables, pool_k,
        )

    nk4 = new_k.reshape(B, Hkv, 1, hd)
    nv4 = new_v.reshape(B, Hkv, 1, hd)
    can_whole = S <= decode_pallas_max_seq(hd, Hkv, Hkv * G, quantized=True)
    # BS must divide S (a floored block count would silently drop the tail —
    # including the current position)
    BS = next((c for c in (256, 128, 64, 32) if S % c == 0), 0)
    if not can_whole and BS == 0:
        # no whole-S fit and no int8-tileable block divides S: exact f32
        # math of the CPU fallback (slower, never wrong)
        return _decode_attend_q8_fallback(
            q, new_k, new_v, cache_k, cache_v, layer, lengths, sc, slot_ids,
            block_tables, pool_k,
        )
    # 1-DMA packed blocks need the scale pseudo-head present in the layout
    packed = p == 1 and os.environ.get("LLM_MCP_TPU_Q8_SCALE_PACK", "1") != "0"
    ids = (
        jnp.arange(B, dtype=jnp.int32)
        if slot_ids is None
        else slot_ids.astype(jnp.int32)
    )
    args = (
        jnp.reshape(layer, (1,)).astype(jnp.int32),
        ids,
        lengths.astype(jnp.int32),
        q,
        nk4,
        nv4,
        cache_k["q"],
        cache_k["s"],
    )
    out_shape = jax.ShapeDtypeStruct((B, Hkv, G, hd), q.dtype)

    def run_whole():
        # whole-S tiles fit VMEM: one payload + one scales DMA per cell,
        # pipelined across grid cells — the cheaper shape once rows are
        # mostly full. The payload block stops at head 2*Hkv: the packed
        # scale pseudo-head is blocked-arm fuel and never enters VMEM here.
        kernel = functools.partial(_attend_q8_kernel, scale=sc)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # layer [1], slot ids [Ba], lengths [Ba]
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, Hkv, G, hd), lambda b, li, ids, lens: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, 1, hd), lambda b, li, ids, lens: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, 1, hd), lambda b, li, ids, lens: (b, 0, 0, 0)),
                # cache tiles follow the compaction indirection: batch cell b
                # reads cache row ids[b]. Head-block index 0 of the ragged
                # (2*Hkv + p) head axis covers exactly the 2*Hkv payload rows.
                pl.BlockSpec(
                    (1, 1, 2 * Hkv, S, hd),
                    lambda b, li, ids, lens: (li[0], ids[b], 0, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, 2 * Hkv, S), lambda b, li, ids, lens: (li[0], ids[b], 0, 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, Hkv, G, hd), lambda b, li, ids, lens: (b, 0, 0, 0)
            ),
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interp
        )(*args)

    def run_blocked():
        # rows stream blockwise from HBM with a dynamic trip count — no
        # VMEM cliff at any S, and only the attended prefix [0, w] is ever
        # read. The r05 layout paid ~2.5 µs/cell of DMA-issue latency over
        # FOUR copies (measured: ~9 ms of fixed cost at 8B B=112); the
        # fused layout issues ONE copy per cell (packed) or two (unpacked).
        Hh = 2 * Hkv + 1 if packed else 2 * Hkv
        kernel = functools.partial(
            _attend_q8_blocked_kernel,
            scale=sc,
            block_s=BS,
            seq_len=S,
            packed=packed,
            scale_dtype=cache_k["s"].dtype,
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # layer [1], slot ids [Ba], lengths [Ba]
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, Hkv, G, hd), lambda b, li, ids, lens: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, 1, hd), lambda b, li, ids, lens: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, 1, hd), lambda b, li, ids, lens: (b, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # fused payload
                pl.BlockSpec(memory_space=pl.ANY),  # plain scales
            ],
            out_specs=pl.BlockSpec(
                (1, Hkv, G, hd), lambda b, li, ids, lens: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((2, Hh, BS, hd), jnp.int8),
                pltpu.VMEM((2, 2 * Hkv, BS), cache_k["s"].dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interp
        )(*args)

    def run_paged():
        # block-indirect arm: BS is pinned to the ledger's block_tokens so
        # table entry j covers exactly the kernel's block j
        nbs = block_tables.shape[1]
        bt = S // nbs
        Hh = 2 * Hkv + 1 if packed else 2 * Hkv
        tblf = jnp.take(block_tables, ids, 0).reshape(-1).astype(jnp.int32)
        kernel = functools.partial(
            _attend_q8_paged_kernel,
            scale=sc,
            block_s=bt,
            seq_len=S,
            packed=packed,
            scale_dtype=cache_k["s"].dtype,
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # layer [1], lengths [Ba], tables [Ba*nbs]
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, Hkv, G, hd), lambda b, li, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, 1, hd), lambda b, li, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, 1, hd), lambda b, li, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # fused payload (arena)
                pl.BlockSpec(memory_space=pl.ANY),  # plain scales (arena)
                pl.BlockSpec(memory_space=pl.ANY),  # fused payload (pool)
                pl.BlockSpec(memory_space=pl.ANY),  # plain scales (pool)
            ],
            out_specs=pl.BlockSpec(
                (1, Hkv, G, hd), lambda b, li, lens, tbl: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((2, Hh, bt, hd), jnp.int8),
                pltpu.VMEM((2, 2 * Hkv, bt), cache_k["s"].dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interp
        )(
            jnp.reshape(layer, (1,)).astype(jnp.int32),
            lengths.astype(jnp.int32),
            tblf,
            q,
            nk4,
            nv4,
            cache_k["q"],
            cache_k["s"],
            pool_k["q"],
            pool_k["s"],
        )

    mode = os.environ.get("LLM_MCP_TPU_Q8_DECODE", "auto")

    def run_contig():
        if mode == "whole" and can_whole:
            return run_whole()
        if mode == "blocked" and BS:
            return run_blocked()
        if not can_whole:
            return run_blocked()
        if BS == 0 or interp:
            # interpret mode keeps the static whole-S choice: a runtime cond
            # would emulate BOTH kernels per call in tests. Parity tests force
            # the blocked arm via LLM_MCP_TPU_Q8_DECODE=blocked instead.
            return run_whole()
        # Runtime hybrid (both executables compile once). The r05 4-DMA layout
        # measured the crossover at ~40% traffic ratio (8B B=112 S=1024: 20.5
        # vs 24.4 ms/step empty, 29.2 vs 24.4 at 88% fill); the fused layout
        # cuts the blocked arm's per-cell fixed cost ~4x, so its win region
        # extends to higher fills — default threshold 0.55 (projected from the
        # r05 fixed-cost split, to be re-measured on hardware; the env knob is
        # the re-tuning surface).
        # Compare the kernels' ACTUAL traffic: whole-S DMAs all B rows in full
        # (parked/pad rows included), blocked streams the attended prefix per
        # active row and ONE block per parked row — so the ratio denominator is
        # B·S, not active·S (normalizing by active rows would overestimate the
        # whole-S path exactly in the low-occupancy regime blocked wins).
        thr = float(os.environ.get("LLM_MCP_TPU_Q8_HYBRID", "0.55"))
        w_eff = jnp.where(lengths < S, jnp.minimum(lengths + 1, S), BS)
        ratio = jnp.sum(w_eff.astype(jnp.float32)) / (B * S)
        return jax.lax.cond(ratio < thr, run_blocked, run_whole)

    if block_tables is None:
        return run_contig()
    nbs = block_tables.shape[1]
    paged_ok = (
        pool_k is not None and nbs > 0 and S % nbs == 0
        and (S // nbs) in (32, 64, 128, 256)
    )
    if not paged_ok:
        # table present but the ledger block size has no int8-tileable arm
        # (the engine gates physical mode on this; belt): exact gather math
        return _decode_attend_q8_fallback(
            q, new_k, new_v, cache_k, cache_v, layer, lengths, sc, slot_ids,
            block_tables, pool_k,
        )
    if mode == "paged":
        return run_paged()
    if interp:
        # a runtime identity-cond would emulate both arms per call in tests;
        # parity tests force the paged kernel via LLM_MCP_TPU_Q8_DECODE=paged,
        # everything else takes the exact gather math
        return _decode_attend_q8_fallback(
            q, new_k, new_v, cache_k, cache_v, layer, lengths, sc, slot_ids,
            block_tables, pool_k,
        )
    # Identity tables (no row references a shared block — the raw-decode
    # case, and every freed slot resets to identity) keep the contiguous
    # dispatch bit-for-bit, hybrid included; only actual sharing pays the
    # table-gather arm.
    n_slots = cache_k["q"].shape[1]
    ident = jnp.all(
        block_tables
        == jnp.arange(n_slots * nbs, dtype=block_tables.dtype).reshape(n_slots, nbs)
    )
    return jax.lax.cond(ident, run_contig, run_paged)


def blocked_dma_count(layout: str, packed: bool = True) -> int:
    """Cache copies per (row, block) cell issued by the blocked decode arms
    (static layout property; `scripts/kernel_bench.py` and the parity-guard
    tests read it rather than re-deriving the copy structure).

      q8_gqa   — 1 packed (K|V|scale pseudo-head in one fused int8 block) or
                 2 unpacked (payload head-slice + plain-scales block)
      bf16_gqa — 2 (split K and V arrays; no scales to carry)
      q8_mla   — 1 (latent payload with inlined rope rows; per-position
                 scales fold via the absorbed-query trick, r05 layout)

    The block-indirect (paged) arms issue the SAME counts — the table adds
    a scalar lookup and a source branch, not copies (the `*_paged`
    layouts are accepted so callers can assert that property).

    The r05 pre-fusion GQA layout issued 4 (kq/ks/vq/vs)."""
    if layout in ("q8_gqa", "q8_gqa_paged"):
        return 1 if packed else 2
    if layout in ("bf16_gqa", "bf16_gqa_paged"):
        return 2
    if layout in ("q8_mla", "q8_mla_paged"):
        return 1
    raise ValueError(f"unknown blocked layout: {layout!r}")


def _attend_bf16_kernel(
    li_ref,  # [1] int32 (scalar prefetch) — layer index
    ids_ref,  # [Ba] int32 (scalar prefetch) — cache row per batch position
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    q_ref,  # [1, 1, G, hd]
    nk_ref,  # [1, 1, 1, hd] — this step's K vector (post-rope)
    nv_ref,  # [1, 1, 1, hd]
    k_ref,  # [1, 1, 1, S, hd] — cache tile, PRE-append
    v_ref,  # [1, 1, 1, S, hd]
    o_ref,  # [1, 1, G, hd]
    *,
    scale: float,
):
    """Whole-S bf16 decode attention, one grid cell = one (batch row, KV
    head) — the bf16 sibling of `_attend_q8_kernel`, with the same
    compaction indirection (slot ids), traced layer index, and exact
    current-position override. A per-(row, head) cell keeps the VMEM
    per-position cost at ~2·hd·2 bytes so the whole-S arm reaches the same
    ~12K-position cap as the q8 arm (`decode_pallas_max_seq`)."""
    b = pl.program_id(0)
    w = lengths_ref[b]
    S = k_ref.shape[3]

    k = k_ref[0, 0, 0]  # [S, hd] cache dtype — fed to the MXU un-upcast
    v = v_ref[0, 0, 0]
    q = q_ref[0, 0]  # [G, hd]
    nk = nk_ref[0, 0, 0].astype(jnp.float32)  # [hd]
    nv = nv_ref[0, 0, 0].astype(jnp.float32)

    s = (
        jax.lax.dot_general(
            q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * scale
    )  # [G, S]
    pos = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
    # the tile holds the PRE-append cache — position w's score/value come
    # from the exact new vectors (append happens outside the kernel)
    s_new = (
        jnp.sum(q.astype(jnp.float32) * nk[None, :], axis=-1, keepdims=True) * scale
    )  # [G, 1]
    s = jnp.where(pos == w, s_new, s)
    s = jnp.where(pos <= w, s, NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p_w = jnp.sum(jnp.where(pos == w, p, 0.0), axis=-1, keepdims=True)  # [G, 1]
    pv = jnp.where(pos == w, 0.0, p)
    ctx = jax.lax.dot_general(
        pv.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [G, hd]
    ctx = ctx + p_w * nv[None, :]
    o_ref[0, 0] = (ctx / l).astype(o_ref.dtype)


def _attend_bf16_blocked_kernel(
    li_ref,  # [1] int32 (scalar prefetch) — layer index
    ids_ref,  # [Ba] int32 (scalar prefetch) — cache row per batch position
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    q_ref,  # [1, Hkv, G, hd] VMEM
    nk_ref,  # [1, Hkv, 1, hd] VMEM
    nv_ref,  # [1, Hkv, 1, hd] VMEM
    k_hbm,  # [L, B, Hkv, S, hd] — stays in HBM (ANY), DMA'd per block
    v_hbm,  # [L, B, Hkv, S, hd]
    o_ref,  # [1, Hkv, G, hd] VMEM out
    k_buf,  # VMEM scratch [2, Hkv, BS, hd] cache dtype (double buffer)
    v_buf,
    sems,  # DMA semaphores [2, 2]
    *,
    scale: float,
    block_s: int,
    seq_len: int,
):
    """Blocked bf16 decode attention — the GQA bf16 sibling of
    `_attend_q8_blocked_kernel`: dynamic trip count streams only the
    attended prefix [0, w], flash-style online softmax across blocks, one
    grid cell = one batch row (all KV heads). Two DMAs per cell (split K and
    V arrays — `blocked_dma_count("bf16_gqa")`); the bf16 cache keeps its
    bare split layout because there are no scale rows to fuse."""
    b = pl.program_id(0)
    li = li_ref[0]
    row = ids_ref[b]
    w = lengths_ref[b]
    BS = block_s
    Hkv = q_ref.shape[1]
    nblk_max = seq_len // BS
    nblk = jnp.clip((w + BS) // BS, 1, nblk_max)
    # parked/free rows (w >= S, engine convention): stream one block
    nblk = jnp.where(w >= seq_len, 1, nblk)

    def copies(j, slot):
        return (
            pltpu.make_async_copy(
                k_hbm.at[li, row, :, pl.ds(j * BS, BS), :],
                k_buf.at[slot],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                v_hbm.at[li, row, :, pl.ds(j * BS, BS), :],
                v_buf.at[slot],
                sems.at[slot, 1],
            ),
        )

    def start(j, slot):
        for c in copies(j, slot):
            c.start()

    def wait(j, slot):
        for c in copies(j, slot):
            c.wait()

    start(0, 0)

    q = q_ref[0]  # [Hkv, G, hd]
    nk = nk_ref[0, :, 0].astype(jnp.float32)  # [Hkv, hd]
    nv = nv_ref[0, :, 0].astype(jnp.float32)
    qc = q.astype(k_buf.dtype)
    s_new = (
        jnp.sum(q.astype(jnp.float32) * nk[:, None, :], axis=-1, keepdims=True) * scale
    )  # [Hkv, G, 1]

    G = q_ref.shape[2]
    hd = q_ref.shape[3]
    acc0 = jnp.zeros((Hkv, G, hd), jnp.float32)
    m0 = jnp.full((Hkv, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nblk)
        def _prefetch():
            start(j + 1, 1 - slot)

        wait(j, slot)
        k = k_buf[slot]  # [Hkv, BS, hd]
        v = v_buf[slot]
        s = (
            jax.lax.dot_general(
                qc, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Hkv, G, BS]
        pos = j * BS + jax.lax.broadcasted_iota(jnp.int32, (1, 1, BS), 2)
        s = jnp.where(pos == w, s_new, s)
        s = jnp.where(pos <= w, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(pos <= w, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        p_w = jnp.sum(jnp.where(pos == w, p, 0.0), axis=-1, keepdims=True)
        pv = jnp.where(pos == w, 0.0, p)
        ctx = jax.lax.dot_general(
            pv.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [Hkv, G, hd]
        acc_new = acc * alpha + ctx + p_w * nv[:, None, :]
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, nblk, body, (acc0, m0, l0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _attend_bf16_paged_kernel(
    li_ref,  # [1] int32 (scalar prefetch) — layer index
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    tbl_ref,  # [Ba * nbs] int32 (scalar prefetch) — flattened block tables
    q_ref,  # [1, Hkv, G, hd] VMEM
    nk_ref,  # [1, Hkv, 1, hd] VMEM
    nv_ref,  # [1, Hkv, 1, hd] VMEM
    k_hbm,  # [L, B, Hkv, S, hd] — slot arena (identity homes), HBM
    v_hbm,  # [L, B, Hkv, S, hd]
    pool_k_hbm,  # [L, PXB, Hkv, bt, hd] — prefix block pool
    pool_v_hbm,  # [L, PXB, Hkv, bt, hd]
    o_ref,  # [1, Hkv, G, hd] VMEM out
    k_buf,  # VMEM scratch [2, Hkv, BS, hd] cache dtype (double buffer)
    v_buf,
    sems,  # DMA semaphores [2, 2]
    *,
    scale: float,
    block_s: int,
    seq_len: int,
):
    """Block-indirect sibling of `_attend_bf16_blocked_kernel`: same math
    and double-buffered streaming, each block's two DMAs (split K/V)
    resolved through the per-row block table — arena home vs. pool row,
    same block shape either way (see `_attend_q8_paged_kernel`)."""
    b = pl.program_id(0)
    li = li_ref[0]
    w = lengths_ref[b]
    BS = block_s
    Hkv = q_ref.shape[1]
    nbs = seq_len // BS
    pool_base = k_hbm.shape[1] * nbs
    nblk = jnp.clip((w + BS) // BS, 1, nbs)
    # parked/free rows (w >= S): one block; freed rows reset to identity
    nblk = jnp.where(w >= seq_len, 1, nblk)

    def arena_copies(phys, slot):
        arow = phys // nbs
        aoff = (phys % nbs) * BS
        return (
            pltpu.make_async_copy(
                k_hbm.at[li, arow, :, pl.ds(aoff, BS), :],
                k_buf.at[slot],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                v_hbm.at[li, arow, :, pl.ds(aoff, BS), :],
                v_buf.at[slot],
                sems.at[slot, 1],
            ),
        )

    def pool_copies(phys, slot):
        prow = phys - pool_base
        return (
            pltpu.make_async_copy(
                pool_k_hbm.at[li, prow], k_buf.at[slot], sems.at[slot, 0]
            ),
            pltpu.make_async_copy(
                pool_v_hbm.at[li, prow], v_buf.at[slot], sems.at[slot, 1]
            ),
        )

    def issue(j, slot, op):
        phys = tbl_ref[b * nbs + j]
        ina = phys < pool_base

        @pl.when(ina)
        def _arena():
            for c in arena_copies(phys, slot):
                getattr(c, op)()

        @pl.when(jnp.logical_not(ina))
        def _pool():
            for c in pool_copies(phys, slot):
                getattr(c, op)()

    issue(0, 0, "start")

    q = q_ref[0]  # [Hkv, G, hd]
    nk = nk_ref[0, :, 0].astype(jnp.float32)  # [Hkv, hd]
    nv = nv_ref[0, :, 0].astype(jnp.float32)
    qc = q.astype(k_buf.dtype)
    s_new = (
        jnp.sum(q.astype(jnp.float32) * nk[:, None, :], axis=-1, keepdims=True) * scale
    )  # [Hkv, G, 1]

    G = q_ref.shape[2]
    hd = q_ref.shape[3]
    acc0 = jnp.zeros((Hkv, G, hd), jnp.float32)
    m0 = jnp.full((Hkv, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nblk)
        def _prefetch():
            issue(j + 1, 1 - slot, "start")

        issue(j, slot, "wait")
        k = k_buf[slot]  # [Hkv, BS, hd]
        v = v_buf[slot]
        s = (
            jax.lax.dot_general(
                qc, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Hkv, G, BS]
        pos = j * BS + jax.lax.broadcasted_iota(jnp.int32, (1, 1, BS), 2)
        s = jnp.where(pos == w, s_new, s)
        s = jnp.where(pos <= w, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(pos <= w, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        p_w = jnp.sum(jnp.where(pos == w, p, 0.0), axis=-1, keepdims=True)
        pv = jnp.where(pos == w, 0.0, p)
        ctx = jax.lax.dot_general(
            pv.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [Hkv, G, hd]
        acc_new = acc * alpha + ctx + p_w * nv[:, None, :]
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, nblk, body, (acc0, m0, l0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _decode_attend_bf16_fallback(
    q, new_k, new_v, cache_k, cache_v, layer, lengths, sc, slot_ids=None,
    block_tables=None, pool_k=None, pool_v=None,
):
    """Exact-f32 einsum mirror of the bf16 kernels' math (whole-S reference
    for the parity tests; the serving path on CPU / multi-chip meshes).
    With `block_tables` the rows gather block-indirectly first."""
    S = cache_k.shape[3]
    k = jax.lax.dynamic_index_in_dim(cache_k, layer, 0, keepdims=False)
    v = jax.lax.dynamic_index_in_dim(cache_v, layer, 0, keepdims=False)
    if block_tables is not None:
        tbl = (
            block_tables
            if slot_ids is None
            else jnp.take(block_tables, slot_ids, 0)
        )
        k = paged_gather(
            k, jax.lax.dynamic_index_in_dim(pool_k, layer, 0, keepdims=False), tbl
        )
        v = paged_gather(
            v, jax.lax.dynamic_index_in_dim(pool_v, layer, 0, keepdims=False), tbl
        )
    elif slot_ids is not None:
        k = jnp.take(k, slot_ids, 0)
        v = jnp.take(v, slot_ids, 0)
    qf = q.astype(jnp.float32)
    s = jnp.einsum("bhgd,bhsd->bhgs", qf, k.astype(jnp.float32)) * sc
    pos = jnp.arange(S)[None, None, None, :]
    w = lengths[:, None, None, None]
    s_new = jnp.einsum("bhgd,bhd->bhg", qf, new_k.astype(jnp.float32)) * sc
    s = jnp.where(pos == w, s_new[..., None], s)
    s = jnp.where(pos <= w, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p_w = jnp.sum(jnp.where(pos == w, p, 0.0), axis=-1)  # [B, Hkv, G]
    pv = jnp.where(pos == w, 0.0, p)
    ctx = jnp.einsum("bhgs,bhsd->bhgd", pv, v.astype(jnp.float32))
    ctx = ctx + p_w[..., None] * new_v.astype(jnp.float32)[:, :, None, :]
    return ctx.astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "scale"))
def decode_attend_bf16(
    q: jnp.ndarray,  # [Ba, Hkv, G, hd] — COMPACT batch (active rows only)
    new_k: jnp.ndarray,  # [Ba, Hkv, hd] — post-rope K for this step
    new_v: jnp.ndarray,  # [Ba, Hkv, hd]
    cache_k: jnp.ndarray,  # [L, B, Hkv, S, hd] — FULL stacked cache, PRE-append
    cache_v: jnp.ndarray,  # [L, B, Hkv, S, hd]
    layer: jnp.ndarray,  # scalar int32
    lengths: jnp.ndarray,  # [Ba] int32 — this step's position per row
    *,
    slot_ids: jnp.ndarray | None = None,  # [Ba] int32 cache rows (None = 1:1)
    block_tables: jnp.ndarray | None = None,  # [n_slots, nbs] int32 physical
    #   block tables (executor/physical.py); None = contiguous layout
    pool_k: jnp.ndarray | None = None,  # prefix pool [L, PXB, Hkv, bt, hd]
    pool_v: jnp.ndarray | None = None,
    scale: float = 0.0,  # query scale (0 = head_dim**-0.5)
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Attention over the bf16 (or f32) split KV cache for one layer of the
    decode step — the bf16 twin of `decode_attend_q8`: same scan-invariant
    PRE-append cache contract, compaction indirection, exact
    current-position override, and runtime whole-S/blocked hybrid
    (`LLM_MCP_TPU_BF16_DECODE` forces an arm, `LLM_MCP_TPU_BF16_HYBRID`
    re-tunes the traffic-ratio threshold). With `block_tables`/pools the
    cache is block-indirect with the same identity-check fast path as
    `decode_attend_q8` (`LLM_MCP_TPU_BF16_DECODE=paged` forces the paged
    arm). Returns ctx [B, Hkv, G, hd]."""
    B, Hkv, G, hd = q.shape
    S = cache_k.shape[3]
    interp = _interpret() if interpret is None else interpret
    sc = scale or hd**-0.5

    if not _HAS_PLTPU:  # pragma: no cover — CPU builds without pallas-tpu
        return _decode_attend_bf16_fallback(
            q, new_k, new_v, cache_k, cache_v, layer, lengths, sc, slot_ids,
            block_tables, pool_k, pool_v,
        )

    nk4 = new_k.reshape(B, Hkv, 1, hd)
    nv4 = new_v.reshape(B, Hkv, 1, hd)
    can_whole = S <= decode_pallas_max_seq(hd, Hkv, Hkv * G, quantized=False)
    # BS must divide S (a floored block count would silently drop the tail)
    BS = next((c for c in (256, 128, 64, 32) if S % c == 0), 0)
    if not can_whole and BS == 0:
        return _decode_attend_bf16_fallback(
            q, new_k, new_v, cache_k, cache_v, layer, lengths, sc, slot_ids,
            block_tables, pool_k, pool_v,
        )
    ids = (
        jnp.arange(B, dtype=jnp.int32)
        if slot_ids is None
        else slot_ids.astype(jnp.int32)
    )
    args = (
        jnp.reshape(layer, (1,)).astype(jnp.int32),
        ids,
        lengths.astype(jnp.int32),
        q,
        nk4,
        nv4,
        cache_k,
        cache_v,
    )
    out_shape = jax.ShapeDtypeStruct((B, Hkv, G, hd), q.dtype)

    def run_whole():
        kernel = functools.partial(_attend_bf16_kernel, scale=sc)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # layer [1], slot ids [Ba], lengths [Ba]
            grid=(B, Hkv),
            in_specs=[
                pl.BlockSpec((1, 1, G, hd), lambda b, h, li, ids, lens: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 1, hd), lambda b, h, li, ids, lens: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 1, hd), lambda b, h, li, ids, lens: (b, h, 0, 0)),
                pl.BlockSpec(
                    (1, 1, 1, S, hd),
                    lambda b, h, li, ids, lens: (li[0], ids[b], h, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, 1, S, hd),
                    lambda b, h, li, ids, lens: (li[0], ids[b], h, 0, 0),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, G, hd), lambda b, h, li, ids, lens: (b, h, 0, 0)
            ),
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interp
        )(*args)

    def run_blocked():
        kernel = functools.partial(
            _attend_bf16_blocked_kernel, scale=sc, block_s=BS, seq_len=S
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # layer [1], slot ids [Ba], lengths [Ba]
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, Hkv, G, hd), lambda b, li, ids, lens: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, 1, hd), lambda b, li, ids, lens: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, 1, hd), lambda b, li, ids, lens: (b, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # K cache
                pl.BlockSpec(memory_space=pl.ANY),  # V cache
            ],
            out_specs=pl.BlockSpec(
                (1, Hkv, G, hd), lambda b, li, ids, lens: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((2, Hkv, BS, hd), cache_k.dtype),
                pltpu.VMEM((2, Hkv, BS, hd), cache_v.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interp
        )(*args)

    def run_paged():
        # block-indirect arm: BS pinned to the ledger's block_tokens
        nbs = block_tables.shape[1]
        bt = S // nbs
        tblf = jnp.take(block_tables, ids, 0).reshape(-1).astype(jnp.int32)
        kernel = functools.partial(
            _attend_bf16_paged_kernel, scale=sc, block_s=bt, seq_len=S
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # layer [1], lengths [Ba], tables [Ba*nbs]
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, Hkv, G, hd), lambda b, li, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, 1, hd), lambda b, li, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, 1, hd), lambda b, li, lens, tbl: (b, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # K arena
                pl.BlockSpec(memory_space=pl.ANY),  # V arena
                pl.BlockSpec(memory_space=pl.ANY),  # K pool
                pl.BlockSpec(memory_space=pl.ANY),  # V pool
            ],
            out_specs=pl.BlockSpec(
                (1, Hkv, G, hd), lambda b, li, lens, tbl: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((2, Hkv, bt, hd), cache_k.dtype),
                pltpu.VMEM((2, Hkv, bt, hd), cache_v.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interp
        )(
            jnp.reshape(layer, (1,)).astype(jnp.int32),
            lengths.astype(jnp.int32),
            tblf,
            q,
            nk4,
            nv4,
            cache_k,
            cache_v,
            pool_k,
            pool_v,
        )

    mode = os.environ.get("LLM_MCP_TPU_BF16_DECODE", "auto")

    def run_contig():
        if mode == "whole" and can_whole:
            return run_whole()
        if mode == "blocked" and BS:
            return run_blocked()
        if not can_whole:
            return run_blocked()
        if BS == 0 or interp:
            # interpret mode keeps the static whole-S choice (same reasoning
            # as decode_attend_q8); parity tests force the blocked arm via
            # LLM_MCP_TPU_BF16_DECODE=blocked.
            return run_whole()
        # Runtime hybrid, same traffic-ratio rule as the q8 path. The bf16
        # blocked arm pays 2 DMAs/cell (split K/V), so its fixed cost sits
        # between the fused-q8 1-copy arm and the r05 4-copy layout — start at
        # the same 0.55 default and re-tune on hardware via the env knob.
        thr = float(os.environ.get("LLM_MCP_TPU_BF16_HYBRID", "0.55"))
        w_eff = jnp.where(lengths < S, jnp.minimum(lengths + 1, S), BS)
        ratio = jnp.sum(w_eff.astype(jnp.float32)) / (B * S)
        return jax.lax.cond(ratio < thr, run_blocked, run_whole)

    if block_tables is None:
        return run_contig()
    nbs = block_tables.shape[1]
    paged_ok = (
        pool_k is not None and nbs > 0 and S % nbs == 0
        and (S // nbs) in (32, 64, 128, 256)
    )
    if not paged_ok or interp and mode != "paged":
        # engine gates physical mode on a tileable block size (belt), and
        # interpret runs keep a static arm choice — exact gather math
        return _decode_attend_bf16_fallback(
            q, new_k, new_v, cache_k, cache_v, layer, lengths, sc, slot_ids,
            block_tables, pool_k, pool_v,
        )
    if mode == "paged":
        return run_paged()
    # identity tables keep the contiguous dispatch (see decode_attend_q8)
    n_slots = cache_k.shape[1]
    ident = jnp.all(
        block_tables
        == jnp.arange(n_slots * nbs, dtype=block_tables.dtype).reshape(n_slots, nbs)
    )
    return jax.lax.cond(ident, run_contig, run_paged)


def _attend_q8_mla_kernel(
    li_ref,  # [1] int32 (scalar prefetch) — layer index
    ids_ref,  # [Ba] int32 (scalar prefetch) — cache row per batch position
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    qt_ref,  # [1, H, R] — absorbed queries (latent space)
    qr_ref,  # [1, H, dr] — rope queries
    nc_ref,  # [1, 1, R] — this step's exact latent
    nr_ref,  # [1, 1, dr] — this step's exact rope key
    lat_ref,  # [1, 1, 1, S, R] int8 — latent payload (cache row ids[b])
    lats_ref,  # [1, 1, 1, S] — latent scales
    rop_ref,  # [1, 1, 1, S, dr] int8 — rope-key payload
    rops_ref,  # [1, 1, 1, S] — rope-key scales
    o_ref,  # [1, H, R] — context in latent space
    *,
    scale: float,
):
    """Absorbed MLA decode attention over the int8 latent cache — one grid
    cell per batch row.

    The absorbed form is MQA-shaped (one shared latent row serves every
    head), so this mirrors `_attend_q8_kernel` at Hkv=1/G=H/hd=R with one
    structural difference: scores take a SECOND additive term from the
    shared rope keys. The latent side (R = 512 at DeepSeek shapes — the
    bulk of the HBM traffic) runs s8 x s8 -> s32 on the MXU with post-dot
    scale folding; the rope side (dr = 64, ~1/9 of the bytes and below the
    128-lane int8 tile width) dequantizes on the VPU and dots in f32.
    Position w's score and value come from the exact unquantized vectors,
    so the current token is attended at full precision whether or not the
    quantized row has been scattered yet.
    """
    b = pl.program_id(0)
    w = lengths_ref[b]
    S = lat_ref.shape[3]

    qt = qt_ref[0].astype(jnp.float32)  # [H, R]
    qr = qr_ref[0].astype(jnp.float32)  # [H, dr]
    nc = nc_ref[0, 0].astype(jnp.float32)  # [R]
    nr = nr_ref[0, 0].astype(jnp.float32)  # [dr]
    lats = lats_ref[0, 0, 0].astype(jnp.float32)  # [S]
    rops = rops_ref[0, 0, 0].astype(jnp.float32)  # [S]

    # latent scores on the MXU: quantize q̃ per head, fold scale post-dot
    qa = jnp.max(jnp.abs(qt), axis=-1)  # [H]
    qsc = jnp.maximum(qa / 127.0, 1e-30)
    qt8 = jnp.round(qt / qsc[:, None]).astype(jnp.int8)
    s_lat_i = jax.lax.dot_general(
        qt8,
        lat_ref[0, 0, 0],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [H, S]
    s = s_lat_i.astype(jnp.float32) * (scale * qsc)[:, None] * lats[None, :]

    # rope scores: S x dr is tiny — dequant on the VPU, f32 dot
    rop = rop_ref[0, 0, 0].astype(jnp.float32) * rops[:, None]  # [S, dr]
    s = s + jax.lax.dot_general(
        qr, rop, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale

    pos = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
    s_new = (
        jnp.sum(qt * nc[None, :], axis=-1) + jnp.sum(qr * nr[None, :], axis=-1)
    ) * scale  # [H]
    s = jnp.where(pos == w, s_new[:, None], s)
    s = jnp.where(pos <= w, s, NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p_w = jnp.sum(jnp.where(pos == w, p, 0.0), axis=-1, keepdims=True)  # [H, 1]
    # fold the latent dequant scales into the probs, quantize the prob rows,
    # and run the PV dot s8 x s8 too
    pv = jnp.where(pos == w, 0.0, p * lats[None, :])  # [H, S]
    pa = jnp.max(pv, axis=-1)
    psc = jnp.maximum(pa / 127.0, 1e-30)
    p8 = jnp.round(pv / psc[:, None]).astype(jnp.int8)
    ctx_i = jax.lax.dot_general(
        p8,
        lat_ref[0, 0, 0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [H, R]
    ctx = ctx_i.astype(jnp.float32) * psc[:, None] + p_w * nc[None, :]
    o_ref[0] = (ctx / l).astype(o_ref.dtype)


def mla_whole_s_fits(S: int, R: int, dr: int, H: int) -> bool:
    """Whole-S VMEM budget for `_attend_q8_mla_kernel`: int8 payloads + the
    f32 working set — three [H, S] score/prob arrays, the [S, dr]
    dequantized rope block, and the [H, R]-class query/context tiles —
    under ~8 MB headroom. Beyond it the BLOCKED variant streams from HBM."""
    return (
        S * (R + dr) + 4 * S * (3 * H + dr) + 4 * H * (2 * R + dr)
    ) <= 8 * 1024 * 1024


def _attend_q8_mla_blocked_kernel(
    li_ref,  # [1] int32 (scalar prefetch) — layer index
    ids_ref,  # [Ba] int32 (scalar prefetch) — cache row per batch position
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    qt_ref,  # [1, H, R] VMEM — absorbed queries (latent space)
    qr_ref,  # [1, H, dr] VMEM — rope queries
    nc_ref,  # [1, 1, R] VMEM — this step's exact latent
    nr_ref,  # [1, 1, dr] VMEM — this step's exact rope key
    lat_hbm,  # [L, B, 1, S, R] int8 — latent payload, stays in HBM (ANY)
    lats_ref,  # [1, 1, 1, S] VMEM — latent scales (whole row via BlockSpec)
    rop_ref,  # [1, 1, 1, S, dr] VMEM — rope payload (whole row: dr < the
    #           128-lane tile, so a manual DMA of a [BS, dr] slice of its
    #           lane-padded HBM layout is rejected; the BlockSpec pipeline
    #           is layout-aware. Rope+scales are ≤1/8 of the latent bytes
    #           and the caller caps S//BS at 64, so whole-row VMEM is ≤3 MB)
    rops_ref,  # [1, 1, 1, S] VMEM — rope scales
    o_ref,  # [1, H, R] VMEM out — context in latent space
    lat_buf,  # VMEM scratch [2, BS, R] int8 (double buffer) — the latent
    #           payload is the real bandwidth and DOES stream blockwise
    sems,  # DMA semaphores [2]
    *,
    scale: float,
    block_s: int,
    seq_len: int,
):
    """Long-context MLA decode attention: the blocked-DMA analog of
    `_attend_q8_mla_kernel` (absorbed MQA-shaped form, second additive
    rope-score term) — the latent row stays in HBM and a double-buffered
    DMA loop streams the attended prefix [0, w], flash-style online softmax
    accumulating the latent-space context across blocks.

    The block loop is a STATIC python unroll over seq_len//BS with every
    DMA gated by `pl.when(j < nblk)`: static block indices keep every
    slice/index in the op classes the whole-S kernel already proves Mosaic
    accepts (dynamic slot/offset forms tripped a parade of tiling-alignment
    rejections: size-1 bf16 sublane slices, (2,128)-tiled f32 row DMA dsts,
    64-lane rope slices). Blocks past nblk skip their DMA; their compute
    runs on stale buffer contents and is a NATURAL no-op — every position
    masks to -inf, so the online-softmax update leaves (acc, m, l)
    unchanged. The caller bounds seq_len//BS (program size is linear in
    it) and falls back to exact math beyond the cap."""
    b = pl.program_id(0)
    li = li_ref[0]
    row = ids_ref[b]
    w = lengths_ref[b]
    BS = block_s
    nblk_max = seq_len // BS
    nblk = jnp.clip((w + BS) // BS, 1, nblk_max)
    # parked/free rows (w >= S) produce discarded output: stream one block
    nblk = jnp.where(w >= seq_len, 1, nblk)

    def copy(j: int, slot: int):
        return pltpu.make_async_copy(
            lat_hbm.at[li, row, 0, pl.ds(j * BS, BS), :], lat_buf.at[slot],
            sems.at[slot],
        )

    def start(j: int, slot: int):
        @pl.when(j < nblk)
        def _():
            copy(j, slot).start()

    def wait(j: int, slot: int):
        @pl.when(j < nblk)
        def _():
            copy(j, slot).wait()

    start(0, 0)

    qt = qt_ref[0].astype(jnp.float32)  # [H, R]
    qr = qr_ref[0].astype(jnp.float32)  # [H, dr]
    nc = nc_ref[0, 0].astype(jnp.float32)  # [R]
    nr = nr_ref[0, 0].astype(jnp.float32)  # [dr]
    qa = jnp.max(jnp.abs(qt), axis=-1)
    qsc = jnp.maximum(qa / 127.0, 1e-30)
    qt8 = jnp.round(qt / qsc[:, None]).astype(jnp.int8)
    s_new = (
        jnp.sum(qt * nc[None, :], axis=-1) + jnp.sum(qr * nr[None, :], axis=-1)
    )[:, None] * scale  # [H, 1]

    H, R = qt.shape
    acc = jnp.zeros((H, R), jnp.float32)
    m = jnp.full((H, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((H, 1), jnp.float32)

    for j in range(nblk_max):  # static unroll; see docstring
        slot = j % 2
        if j + 1 < nblk_max:
            start(j + 1, 1 - slot)
        wait(j, slot)
        lat = lat_buf[slot]  # [BS, R] int8
        # static block slices of the BlockSpec-delivered rows (j is a
        # python int: every start is a provable tile multiple)
        lats = lats_ref[0, 0, 0, j * BS:(j + 1) * BS].astype(jnp.float32)
        # latent scores: s8 x s8 -> s32 on the MXU, post-dot scale fold
        s_i = jax.lax.dot_general(
            qt8, lat, (((1,), (1,)), ((), ())), preferred_element_type=jnp.int32
        )  # [H, BS]
        s = s_i.astype(jnp.float32) * (scale * qsc)[:, None] * lats[None, :]
        # rope scores: BS x dr is tiny — dequant on the VPU, f32 dot
        rops = rops_ref[0, 0, 0, j * BS:(j + 1) * BS].astype(jnp.float32)
        rop = rop_ref[0, 0, 0, j * BS:(j + 1) * BS, :].astype(jnp.float32) * rops[:, None]
        s = s + jax.lax.dot_general(
            qr, rop, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        pos = j * BS + jax.lax.broadcasted_iota(jnp.int32, (1, BS), 1)
        # skipped blocks (j >= nblk) hold STALE buffer bytes — every mask
        # must also gate on the block being live, or a parked row (w >= S,
        # so pos <= w everywhere) would exponentiate garbage into NaN
        live = pos <= jnp.where(j < nblk, w, -1)
        cur = live & (pos == w)
        s = jnp.where(cur, s_new, s)
        s = jnp.where(live, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(live, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        p_w = jnp.sum(jnp.where(cur, p, 0.0), axis=-1, keepdims=True)
        # fold latent dequant scales into the probs, requantize, PV on MXU.
        # Gate on `live`, not just ~cur: a skipped block's stale lats can be
        # NaN and 0 * NaN = NaN would poison the accumulator.
        pv = jnp.where(live & ~cur, p * lats[None, :], 0.0)  # [H, BS]
        pa = jnp.max(pv, axis=-1)
        psc = jnp.maximum(pa / 127.0, 1e-30)
        p8 = jnp.round(pv / psc[:, None]).astype(jnp.int8)
        ctx_i = jax.lax.dot_general(
            p8, lat, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
        )  # [H, R]
        acc = acc * alpha + ctx_i.astype(jnp.float32) * psc[:, None] + p_w * nc[None, :]
        m = m_new

    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _attend_q8_mla_paged_kernel(
    li_ref,  # [1] int32 (scalar prefetch) — layer index
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    tbl_ref,  # [Ba * nbs] int32 (scalar prefetch) — flattened block tables
    qt_ref,  # [1, H, R] VMEM — absorbed queries (latent space)
    qr_ref,  # [1, H, dr] VMEM — rope queries
    nc_ref,  # [1, 1, R] VMEM — this step's exact latent
    nr_ref,  # [1, 1, dr] VMEM — this step's exact rope key
    lat_hbm,  # [L, B, 1, S, R] int8 — latent arena (identity homes), HBM
    pool_lat_hbm,  # [L, PXB, 1, bt, R] int8 — latent prefix pool, HBM
    lats_ref,  # [1, S] VMEM — latent scales, PRE-GATHERED through the table
    rop_ref,  # [1, S, dr] VMEM — rope payload, PRE-GATHERED
    rops_ref,  # [1, S] VMEM — rope scales, PRE-GATHERED
    o_ref,  # [1, H, R] VMEM out — context in latent space
    lat_buf,  # VMEM scratch [2, BS, R] int8 (double buffer)
    sems,  # DMA semaphores [2]
    *,
    scale: float,
    block_s: int,
    seq_len: int,
):
    """Block-indirect sibling of `_attend_q8_mla_blocked_kernel`: the
    latent payload — ~8/9 of the bytes — streams through the per-row block
    table (arena home vs. pool row, one DMA per block either way); the
    rope payload and both scale rows arrive PRE-GATHERED by the caller
    (`paged_gather` in XLA) because their whole-row BlockSpec rides index
    a single cache row and a [BS, dr]/[1, BS]-class manual DMA is exactly
    the op Mosaic rejected when the blocked kernel was built (see its
    docstring). Same static unroll + `pl.when`-gated DMAs + live-masked
    stale-block no-ops as the blocked variant; BS equals the ledger's
    block_tokens so table entry j covers kernel block j."""
    b = pl.program_id(0)
    li = li_ref[0]
    w = lengths_ref[b]
    BS = block_s
    nbs = seq_len // BS
    pool_base = lat_hbm.shape[1] * nbs
    nblk = jnp.clip((w + BS) // BS, 1, nbs)
    # parked/free rows (w >= S) stream one block; freed rows are identity
    nblk = jnp.where(w >= seq_len, 1, nblk)

    def issue(j: int, slot: int, op: str):
        phys = tbl_ref[b * nbs + j]
        ina = phys < pool_base

        @pl.when((j < nblk) & ina)
        def _arena():
            c = pltpu.make_async_copy(
                lat_hbm.at[li, phys // nbs, 0, pl.ds((phys % nbs) * BS, BS), :],
                lat_buf.at[slot],
                sems.at[slot],
            )
            getattr(c, op)()

        @pl.when((j < nblk) & jnp.logical_not(ina))
        def _pool():
            c = pltpu.make_async_copy(
                pool_lat_hbm.at[li, phys - pool_base, 0],
                lat_buf.at[slot],
                sems.at[slot],
            )
            getattr(c, op)()

    issue(0, 0, "start")

    qt = qt_ref[0].astype(jnp.float32)  # [H, R]
    qr = qr_ref[0].astype(jnp.float32)  # [H, dr]
    nc = nc_ref[0, 0].astype(jnp.float32)  # [R]
    nr = nr_ref[0, 0].astype(jnp.float32)  # [dr]
    qa = jnp.max(jnp.abs(qt), axis=-1)
    qsc = jnp.maximum(qa / 127.0, 1e-30)
    qt8 = jnp.round(qt / qsc[:, None]).astype(jnp.int8)
    s_new = (
        jnp.sum(qt * nc[None, :], axis=-1) + jnp.sum(qr * nr[None, :], axis=-1)
    )[:, None] * scale  # [H, 1]

    H, R = qt.shape
    acc = jnp.zeros((H, R), jnp.float32)
    m = jnp.full((H, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((H, 1), jnp.float32)

    for j in range(nbs):  # static unroll; see blocked kernel's docstring
        slot = j % 2
        if j + 1 < nbs:
            issue(j + 1, 1 - slot, "start")
        issue(j, slot, "wait")
        lat = lat_buf[slot]  # [BS, R] int8
        lats = lats_ref[0, j * BS:(j + 1) * BS].astype(jnp.float32)
        s_i = jax.lax.dot_general(
            qt8, lat, (((1,), (1,)), ((), ())), preferred_element_type=jnp.int32
        )  # [H, BS]
        s = s_i.astype(jnp.float32) * (scale * qsc)[:, None] * lats[None, :]
        rops = rops_ref[0, j * BS:(j + 1) * BS].astype(jnp.float32)
        rop = rop_ref[0, j * BS:(j + 1) * BS, :].astype(jnp.float32) * rops[:, None]
        s = s + jax.lax.dot_general(
            qr, rop, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        pos = j * BS + jax.lax.broadcasted_iota(jnp.int32, (1, BS), 1)
        # skipped blocks (j >= nblk) hold STALE buffer bytes — gate every
        # mask on liveness (same invariant as the blocked kernel)
        live = pos <= jnp.where(j < nblk, w, -1)
        cur = live & (pos == w)
        s = jnp.where(cur, s_new, s)
        s = jnp.where(live, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(live, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        p_w = jnp.sum(jnp.where(cur, p, 0.0), axis=-1, keepdims=True)
        pv = jnp.where(live & ~cur, p * lats[None, :], 0.0)  # [H, BS]
        pa = jnp.max(pv, axis=-1)
        psc = jnp.maximum(pa / 127.0, 1e-30)
        p8 = jnp.round(pv / psc[:, None]).astype(jnp.int8)
        ctx_i = jax.lax.dot_general(
            p8, lat, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
        )  # [H, R]
        acc = acc * alpha + ctx_i.astype(jnp.float32) * psc[:, None] + p_w * nc[None, :]
        m = m_new

    o_ref[0] = (acc / l).astype(o_ref.dtype)


def mla_block_size(seq_len: int) -> int:
    """Block size for `_attend_q8_mla_blocked_kernel`, 0 = no blocked arm.

    BS must divide S (a floored trip count would drop the tail — including
    the current position). The kernel's block loop is a STATIC python
    unroll (see its docstring), so program size is linear in S//BS: past 64
    blocks (S=32768 at BS=512 is exactly the boundary) compile time
    outgrows the win and `decode_attend_q8_mla` falls back to exact f32
    math instead."""
    bs = next((c for c in (512, 256, 128) if seq_len % c == 0), 0)
    if bs and seq_len // bs > 64:
        return 0
    return bs


def _decode_attend_q8_mla_fallback(
    qt, qr, new_c, new_r, cache_c, cache_r, layer, lengths, scale, slot_ids,
    block_tables=None, pool_c=None, pool_r=None,
):
    """Exact f32 math of the MLA kernel (CPU / unfit shapes): pre-append
    semantics with the current position overridden by the exact vectors.
    With `block_tables` every cache read gathers block-indirectly."""
    Ba = qt.shape[0]

    def rowsel(x):
        return x if slot_ids is None else jnp.take(x, slot_ids, axis=0)

    if block_tables is not None:
        tbl = (
            block_tables
            if slot_ids is None
            else jnp.take(block_tables, slot_ids, 0)
        )

    def sel(entry, pool_entry=None):
        a = jax.lax.dynamic_index_in_dim(entry, layer, 0, keepdims=False)
        if block_tables is None:
            return rowsel(a[:, 0])
        p = jax.lax.dynamic_index_in_dim(pool_entry, layer, 0, keepdims=False)
        return paged_gather(a, p, tbl)[:, 0]

    lat = sel(cache_c["q"], pool_c and pool_c["q"]).astype(jnp.float32)  # [Ba,S,R]
    rop = sel(cache_r["q"], pool_r and pool_r["q"]).astype(jnp.float32)  # [Ba,S,dr]
    ls = sel(cache_c["s"], pool_c and pool_c["s"]).astype(jnp.float32)  # [Ba, S]
    rs = sel(cache_r["s"], pool_r and pool_r["s"]).astype(jnp.float32)
    S = lat.shape[1]
    qtf = qt.astype(jnp.float32)
    qrf = qr.astype(jnp.float32)
    s = (
        jnp.einsum("bhr,bsr->bhs", qtf, lat) * ls[:, None, :]
        + jnp.einsum("bhd,bsd->bhs", qrf, rop) * rs[:, None, :]
    ) * scale
    pos = jnp.arange(S)[None, None, :]
    w = lengths[:, None, None]
    s_new = (
        jnp.einsum("bhr,br->bh", qtf, new_c.astype(jnp.float32))
        + jnp.einsum("bhd,bd->bh", qrf, new_r.astype(jnp.float32))
    ) * scale
    s = jnp.where(pos == w, s_new[..., None], s)
    s = jnp.where(pos <= w, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p_w = jnp.sum(jnp.where(pos == w, p, 0.0), axis=-1)  # [Ba, H]
    pl_ = jnp.where(pos == w, 0.0, p * ls[:, None, :])
    ctx = jnp.einsum("bhs,bsr->bhr", pl_, lat) + p_w[..., None] * new_c.astype(
        jnp.float32
    )[:, None, :]
    return ctx.astype(qt.dtype)


def decode_attend_q8_mla(
    qt: jnp.ndarray,  # [Ba, H, R] — absorbed queries (latent space)
    qr: jnp.ndarray,  # [Ba, H, dr] — rope queries
    new_c: jnp.ndarray,  # [Ba, R] — this step's exact latent
    new_r: jnp.ndarray,  # [Ba, dr] — this step's exact rope key
    cache_c: dict,  # {"q": int8 [L,B,1,S,R], "s": [L,B,1,S]}
    cache_r: dict,  # {"q": int8 [L,B,1,S,dr], "s": [L,B,1,S]}
    layer: jnp.ndarray,  # scalar int32
    lengths: jnp.ndarray,  # [Ba] int32 — this step's position per row
    *,
    slot_ids: jnp.ndarray | None = None,
    block_tables: jnp.ndarray | None = None,  # [n_slots, nbs] int32 physical
    #   block tables (executor/physical.py); None = contiguous layout
    pool_c: dict | None = None,  # latent prefix pool mirroring cache_c
    pool_r: dict | None = None,  # rope prefix pool mirroring cache_r
    scale: float,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Absorbed MLA decode attention over the int8 latent cache for one
    layer — the s8-MXU replacement for the XLA dequant-then-dot path
    (models/mla.py). Returns ctx in latent space [Ba, H, R]; the caller
    owns the cache append (the kernel overrides position w exactly).

    Falls back to exact f32 math off-TPU or when R isn't a 128-lane
    multiple (tiny test configs). Past the whole-S kernel's VMEM budget,
    the BLOCKED variant streams the latent row from HBM with a dynamic
    trip count (`_attend_q8_mla_blocked_kernel`) — int8-latent long
    context (S=32k) runs on the MXU too. With `block_tables`/pools the
    latent payload streams block-indirectly
    (`_attend_q8_mla_paged_kernel`, identity-table fast path as in
    `decode_attend_q8`; `LLM_MCP_TPU_Q8_DECODE=paged` forces the arm)."""
    Ba, H, R = qt.shape
    dr = qr.shape[-1]
    S = cache_c["q"].shape[3]
    interp = _interpret() if interpret is None else interpret
    fits = mla_whole_s_fits(S, R, dr, H)
    BS = mla_block_size(S)
    if not _HAS_PLTPU or (not fits and BS == 0) or (not interp and R % 128 != 0):
        return _decode_attend_q8_mla_fallback(
            qt, qr, new_c, new_r, cache_c, cache_r, layer, lengths, scale, slot_ids,
            block_tables, pool_c, pool_r,
        )

    ids = (
        jnp.arange(Ba, dtype=jnp.int32)
        if slot_ids is None
        else slot_ids.astype(jnp.int32)
    )
    args = (
        jnp.reshape(layer, (1,)).astype(jnp.int32),
        ids,
        lengths.astype(jnp.int32),
        qt,
        qr,
        new_c.reshape(Ba, 1, R),
        new_r.reshape(Ba, 1, dr),
        cache_c["q"],
        cache_c["s"],
        cache_r["q"],
        cache_r["s"],
    )
    out_shape = jax.ShapeDtypeStruct((Ba, H, R), qt.dtype)

    def run_whole():
        kernel = functools.partial(_attend_q8_mla_kernel, scale=scale)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # layer [1], slot ids [Ba], lengths [Ba]
            grid=(Ba,),
            in_specs=[
                pl.BlockSpec((1, H, R), lambda b, li, ids, lens: (b, 0, 0)),
                pl.BlockSpec((1, H, dr), lambda b, li, ids, lens: (b, 0, 0)),
                pl.BlockSpec((1, 1, R), lambda b, li, ids, lens: (b, 0, 0)),
                pl.BlockSpec((1, 1, dr), lambda b, li, ids, lens: (b, 0, 0)),
                pl.BlockSpec(
                    (1, 1, 1, S, R), lambda b, li, ids, lens: (li[0], ids[b], 0, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, S), lambda b, li, ids, lens: (li[0], ids[b], 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, S, dr), lambda b, li, ids, lens: (li[0], ids[b], 0, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, S), lambda b, li, ids, lens: (li[0], ids[b], 0, 0)
                ),
            ],
            out_specs=pl.BlockSpec((1, H, R), lambda b, li, ids, lens: (b, 0, 0)),
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interp
        )(*args)

    def run_blocked():
        kernel = functools.partial(
            _attend_q8_mla_blocked_kernel, scale=scale, block_s=BS, seq_len=S
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # layer [1], slot ids [Ba], lengths [Ba]
            grid=(Ba,),
            in_specs=[
                pl.BlockSpec((1, H, R), lambda b, li, ids, lens: (b, 0, 0)),
                pl.BlockSpec((1, H, dr), lambda b, li, ids, lens: (b, 0, 0)),
                pl.BlockSpec((1, 1, R), lambda b, li, ids, lens: (b, 0, 0)),
                pl.BlockSpec((1, 1, dr), lambda b, li, ids, lens: (b, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # latent payload (DMA'd)
                # scales + the (small, lane-padded) rope row ride the
                # layout-aware BlockSpec pipeline — see kernel docstring
                pl.BlockSpec(
                    (1, 1, 1, S), lambda b, li, ids, lens: (li[0], ids[b], 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, S, dr), lambda b, li, ids, lens: (li[0], ids[b], 0, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, S), lambda b, li, ids, lens: (li[0], ids[b], 0, 0)
                ),
            ],
            out_specs=pl.BlockSpec((1, H, R), lambda b, li, ids, lens: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, BS, R), jnp.int8),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interp
        )(*args)

    def run_paged():
        # latent payload streams through the table; rope + scales are
        # PRE-GATHERED contiguous-equivalent rows (see the paged kernel's
        # docstring for why they can't ride a per-block DMA)
        nbs = block_tables.shape[1]
        bt = S // nbs
        tblc = jnp.take(block_tables, ids, 0).astype(jnp.int32)
        lat_a = jax.lax.dynamic_index_in_dim(cache_c["s"], layer, 0, keepdims=False)
        lat_p = jax.lax.dynamic_index_in_dim(pool_c["s"], layer, 0, keepdims=False)
        lats_g = paged_gather(lat_a, lat_p, tblc)[:, 0]  # [Ba, S]
        rop_a = jax.lax.dynamic_index_in_dim(cache_r["q"], layer, 0, keepdims=False)
        rop_p = jax.lax.dynamic_index_in_dim(pool_r["q"], layer, 0, keepdims=False)
        rop_g = paged_gather(rop_a, rop_p, tblc)[:, 0]  # [Ba, S, dr]
        rops_a = jax.lax.dynamic_index_in_dim(cache_r["s"], layer, 0, keepdims=False)
        rops_p = jax.lax.dynamic_index_in_dim(pool_r["s"], layer, 0, keepdims=False)
        rops_g = paged_gather(rops_a, rops_p, tblc)[:, 0]  # [Ba, S]
        kernel = functools.partial(
            _attend_q8_mla_paged_kernel, scale=scale, block_s=bt, seq_len=S
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # layer [1], lengths [Ba], tables [Ba*nbs]
            grid=(Ba,),
            in_specs=[
                pl.BlockSpec((1, H, R), lambda b, li, lens, tbl: (b, 0, 0)),
                pl.BlockSpec((1, H, dr), lambda b, li, lens, tbl: (b, 0, 0)),
                pl.BlockSpec((1, 1, R), lambda b, li, lens, tbl: (b, 0, 0)),
                pl.BlockSpec((1, 1, dr), lambda b, li, lens, tbl: (b, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # latent arena (DMA'd)
                pl.BlockSpec(memory_space=pl.ANY),  # latent pool (DMA'd)
                pl.BlockSpec((1, S), lambda b, li, lens, tbl: (b, 0)),
                pl.BlockSpec((1, S, dr), lambda b, li, lens, tbl: (b, 0, 0)),
                pl.BlockSpec((1, S), lambda b, li, lens, tbl: (b, 0)),
            ],
            out_specs=pl.BlockSpec((1, H, R), lambda b, li, lens, tbl: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, bt, R), jnp.int8),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interp
        )(
            jnp.reshape(layer, (1,)).astype(jnp.int32),
            lengths.astype(jnp.int32),
            tblc.reshape(-1),
            qt,
            qr,
            new_c.reshape(Ba, 1, R),
            new_r.reshape(Ba, 1, dr),
            cache_c["q"],
            pool_c["q"],
            lats_g,
            rop_g,
            rops_g,
        )

    # STATIC selection (unlike decode_attend_q8's runtime hybrid): measured
    # at mla-8b kv8 B=32 S=2048, whole-S beats blocked even at low fill
    # (1845 vs 1653 tok/s — the absorbed form is MQA-shaped, so whole-S
    # cells amortize one huge row DMA over ALL heads and the traffic-ratio
    # trade that pays off for GQA does not appear). The blocked kernel's
    # job is S past the VMEM budget — int8-latent long context on the MXU
    # instead of the XLA dequant path — and it covers a BOUNDED window:
    # `mla_block_size` zeroes BS past 64 static-unroll blocks (S=32768 at
    # BS=512 is the last in-window size), after which the early fallback
    # above already returned exact f32 math. "Whole if it fits, else
    # blocked" below can therefore assume BS > 0.
    mode = os.environ.get("LLM_MCP_TPU_Q8_DECODE", "auto")

    def run_contig():
        if mode == "whole" and fits:
            return run_whole()
        if mode == "blocked" and BS:
            return run_blocked()
        return run_whole() if fits else run_blocked()

    if block_tables is None:
        return run_contig()
    nbs_t = block_tables.shape[1]
    # paged arm shares the blocked kernel's static-unroll budget (≤ 64
    # blocks) and needs an int8-tileable block size
    paged_ok = (
        pool_c is not None and nbs_t > 0 and S % nbs_t == 0
        and (S // nbs_t) >= 32 and nbs_t <= 64
    )
    if mode == "paged" and paged_ok:
        return run_paged()
    if interp or not paged_ok:
        # interpret runs keep a static arm choice (parity tests force the
        # paged kernel via LLM_MCP_TPU_Q8_DECODE=paged); unfit block sizes
        # take the exact gather math
        return _decode_attend_q8_mla_fallback(
            qt, qr, new_c, new_r, cache_c, cache_r, layer, lengths, scale, slot_ids,
            block_tables, pool_c, pool_r,
        )
    # identity tables keep the contiguous dispatch (see decode_attend_q8)
    n_slots = cache_c["q"].shape[1]
    ident = jnp.all(
        block_tables
        == jnp.arange(n_slots * nbs_t, dtype=block_tables.dtype).reshape(
            n_slots, nbs_t
        )
    )
    return jax.lax.cond(ident, run_contig, run_paged)


def _append_q8_kernel(
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    ids_ref,  # [Ba] int32 (scalar prefetch) — cache row per batch position
    #          (consumed by the BlockSpec index maps only: grid cell b's
    #          cache tiles are selected at row ids[b], the body never reads it)
    pay_ref,  # [L, 1, Hf, hd] int8 — this step's FUSED row: quantized K
    #           heads, V heads, packed-scale bytes (built by append_kv_q8
    #           in plain JAX — the kernel only selects, never quantizes)
    s_ref,  # [L, 1, 2*Hkv] — this step's plain dequant scales
    cq_ref,  # [L, 1, Hf, BSQ, hd] int8 — payload tile containing position w
    cs_ref,  # [L, 1, 2*Hkv, BSS] — scales tile containing position w
    oq_ref,  # outputs — aliased to the cache operands
    os_ref,
    *,
    block_q: int,  # payload S-tile (32: int8 sublane height)
    block_s: int,  # scales S-tile (128: lane width)
    seq_len: int,
):
    b = pl.program_id(0)
    w = lengths_ref[b]
    live = w < seq_len  # parked rows (w >= S) must not write anywhere
    wq = jnp.minimum(w, seq_len - 1) % block_q  # payload row within its tile
    ws = jnp.minimum(w, seq_len - 1) % block_s  # scale lane within its tile

    rows = jax.lax.broadcasted_iota(jnp.int32, (1, 1, block_q, 1), 2)  # [1,1,BSQ,1]
    hit = live & (rows == wq)
    oq_ref[:, 0] = jnp.where(hit, pay_ref[:, 0][:, :, None, :], cq_ref[:, 0])
    lanes = jax.lax.broadcasted_iota(jnp.int32, (1, 1, block_s), 2)  # [1,1,BSS]
    hit_s = live & (lanes == ws)
    os_ref[:, 0] = jnp.where(hit_s, s_ref[:, 0][:, :, None].astype(os_ref.dtype), cs_ref[:, 0])


@functools.partial(jax.jit, static_argnames=("interpret",))
def append_kv_q8(
    cache_k: dict,  # FUSED: {"q": int8 [L,B,2*Hkv+p,S,hd], "s": [L,B,2*Hkv,S]}
    cache_v: dict,  # {} — passed through untouched
    new_k: jnp.ndarray,  # [L, Ba, Hkv, hd] — post-rope K for this step, all layers
    new_v: jnp.ndarray,
    lengths: jnp.ndarray,  # [Ba] int32 — write position per row (>= S: skip)
    *,
    slot_ids: jnp.ndarray | None = None,  # [Ba] int32 cache rows (None = 1:1)
    interpret: bool | None = None,
) -> tuple[dict, dict]:
    """Append one decode step's K/V (all layers at once) into the FUSED int8
    cache IN PLACE.

    The XLA scatter alternative (`.at[l_idx, b_idx, h_idx, w_idx].set`)
    copies the entire cache payload per call — measured 6.4 ms of a ~30 ms
    decode step at 8B B=112 S=1024, and 14.2 ms when issued per-layer inside
    the scan. This kernel aliases the cache operands to its outputs and
    rewrites only the 32-row (b, w-tile) block holding each row's position:
    ~0.5 GB of tile traffic instead of ~4 GB of full-buffer copies. Parked
    rows (lengths >= S, see executor/engine.py) write nothing.

    Quantization AND scale-packing happen outside the kernel in plain JAX
    on the tiny [L, Ba, Hkv, hd] step tensors (the bitcast lane-packing of
    `pack_scales` has no proven in-kernel store form; the kernel body only
    selects rows), producing one fused [L, Ba, Hf, hd] row per slot whose
    bytes are written in a single aliased tile pass.
    """
    L, B, Hf, S, hd = cache_k["q"].shape
    Hs = cache_k["s"].shape[2]
    Hkv = Hs // 2
    p = Hf - Hs
    Ba = new_k.shape[1]
    sdt = cache_k["s"].dtype
    interp = _interpret() if interpret is None else interpret
    rows = (
        jnp.arange(Ba, dtype=jnp.int32)
        if slot_ids is None
        else slot_ids.astype(jnp.int32)
    )
    from ..models.llama import quantize_kv  # local import: avoid cycle
    from ..models.quant import pack_scales

    kq = quantize_kv(new_k, scale_dtype=sdt)
    vq = quantize_kv(new_v, scale_dtype=sdt)
    s_new = jnp.concatenate([kq["s"], vq["s"]], axis=2)  # [L, Ba, 2*Hkv]
    pay = jnp.concatenate([kq["q"], vq["q"]], axis=2)  # [L, Ba, 2*Hkv, hd]
    if p:
        # the packed pseudo-head row for this position: [L, Ba, 1, hd]
        pay = jnp.concatenate([pay, pack_scales(s_new[..., None], hd)[..., 0, :]], 2)

    # mosaic int8 stores want full 128-lane rows; small-head test configs
    # (hd 32/64) take the scatter fallback. Interpret mode keeps the kernel
    # path at lane-aligned shapes so parity tests cover the real tile-
    # rewrite body.
    if not _HAS_PLTPU or hd % 128 != 0 or S % 128 != 0:
        # XLA fallback (CPU tests / no pallas-tpu): plain scatter, with OOB
        # (parked) rows dropped by scatter semantics.
        l_idx = jnp.arange(L)[:, None, None]
        b_idx = rows[None, :, None]
        w_idx = lengths[None, :, None]
        ck = {
            "q": cache_k["q"]
            .at[l_idx, b_idx, jnp.arange(Hf)[None, None, :], w_idx]
            .set(pay),
            "s": cache_k["s"]
            .at[l_idx, b_idx, jnp.arange(Hs)[None, None, :], w_idx]
            .set(s_new),
        }
        return ck, cache_v

    BSQ = 32  # int8 sublane tile height: smallest in-place payload rewrite
    BSS = 128  # lane width: smallest in-place scales rewrite
    assert S % BSQ == 0 and S % BSS == 0, (S, BSQ, BSS)
    kernel = functools.partial(_append_q8_kernel, block_q=BSQ, block_s=BSS, seq_len=S)

    def blkq(lens, b):
        # payload tile holding this row's write position (clamped if parked)
        return jnp.minimum(lens[b], S - 1) // BSQ

    def blks(lens, b):
        return jnp.minimum(lens[b], S - 1) // BSS

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # lengths [Ba], cache row ids [Ba]
        grid=(Ba,),
        in_specs=[
            pl.BlockSpec((L, 1, Hf, hd), lambda b, lens, ids: (0, b, 0, 0)),
            pl.BlockSpec((L, 1, Hs), lambda b, lens, ids: (0, b, 0)),
            pl.BlockSpec(
                (L, 1, Hf, BSQ, hd), lambda b, lens, ids: (0, ids[b], 0, blkq(lens, b), 0)
            ),
            pl.BlockSpec(
                (L, 1, Hs, BSS), lambda b, lens, ids: (0, ids[b], 0, blks(lens, b))
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (L, 1, Hf, BSQ, hd), lambda b, lens, ids: (0, ids[b], 0, blkq(lens, b), 0)
            ),
            pl.BlockSpec(
                (L, 1, Hs, BSS), lambda b, lens, ids: (0, ids[b], 0, blks(lens, b))
            ),
        ],
    )
    oq, os_ = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(cache_k["q"].shape, cache_k["q"].dtype),
            jax.ShapeDtypeStruct(cache_k["s"].shape, cache_k["s"].dtype),
        ],
        # operand indices include the prefetch scalars: lengths=0, ids=1,
        # pay=2, s_new=3, cq=4, cs=5 → outputs 0..1
        input_output_aliases={4: 0, 5: 1},
        interpret=interp,
    )(
        lengths.astype(jnp.int32),
        rows,
        pay,
        s_new,
        cache_k["q"],
        cache_k["s"],
    )
    return {"q": oq, "s": os_}, cache_v


def _append_bf16_kernel(
    lengths_ref,  # [Ba] int32 (scalar prefetch) — this step's position per row
    ids_ref,  # [Ba] int32 (scalar prefetch) — cache row per batch position
    nk_ref,  # [L, 1, Hkv, hd] — this step's K vectors (post-rope)
    nv_ref,  # [L, 1, Hkv, hd]
    ck_ref,  # [L, 1, Hkv, BQ, hd] — K tile containing position w
    cv_ref,  # [L, 1, Hkv, BQ, hd]
    ok_ref,  # outputs — aliased to the cache operands
    ov_ref,
    *,
    block_q: int,  # S-tile (16: bf16 sublane height; also divides f32's 8)
    seq_len: int,
):
    b = pl.program_id(0)
    w = lengths_ref[b]
    live = w < seq_len  # parked rows (w >= S) must not write anywhere
    wq = jnp.minimum(w, seq_len - 1) % block_q

    rows = jax.lax.broadcasted_iota(jnp.int32, (1, 1, block_q, 1), 2)  # [1,1,BQ,1]
    hit = live & (rows == wq)
    ok_ref[:, 0] = jnp.where(hit, nk_ref[:, 0][:, :, None, :].astype(ok_ref.dtype), ck_ref[:, 0])
    ov_ref[:, 0] = jnp.where(hit, nv_ref[:, 0][:, :, None, :].astype(ov_ref.dtype), cv_ref[:, 0])


@functools.partial(jax.jit, static_argnames=("interpret",))
def append_kv_bf16(
    cache_k: jnp.ndarray,  # [L, B, Hkv, S, hd] bf16/f32
    cache_v: jnp.ndarray,
    new_k: jnp.ndarray,  # [L, Ba, Hkv, hd] — post-rope K for this step, all layers
    new_v: jnp.ndarray,
    lengths: jnp.ndarray,  # [Ba] int32 — write position per row (>= S: skip)
    *,
    slot_ids: jnp.ndarray | None = None,  # [Ba] int32 cache rows (None = 1:1)
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Append one decode step's K/V (all layers at once) into the bf16 cache
    IN PLACE — the bf16 twin of `append_kv_q8`: aliased cache operands,
    only the 16-row (b, w-tile) block holding each row's position is
    rewritten, parked rows (lengths >= S) write nothing. This is what lets
    `_decode_step_bf16` keep the cache scan-invariant (no per-layer
    dynamic_update_slice copies inside the scan) and batch the whole
    append into one pass after the layer scan."""
    L, B, Hkv, S, hd = cache_k.shape
    Ba = new_k.shape[1]
    interp = _interpret() if interpret is None else interpret
    rows = (
        jnp.arange(Ba, dtype=jnp.int32)
        if slot_ids is None
        else slot_ids.astype(jnp.int32)
    )

    BQ = 16  # bf16 sublane tile height (f32 needs 8 — 16 covers both)
    # mosaic stores want full 128-lane rows; small-head test configs take
    # the scatter fallback. Interpret mode keeps the kernel path at lane-
    # aligned shapes so parity tests cover the real tile-rewrite body.
    if not _HAS_PLTPU or hd % 128 != 0 or S % BQ != 0:
        # XLA fallback (CPU tests / no pallas-tpu): plain scatter, with OOB
        # (parked) rows dropped by scatter semantics.
        l_idx = jnp.arange(L)[:, None, None]
        b_idx = rows[None, :, None]
        h_idx = jnp.arange(Hkv)[None, None, :]
        w_idx = lengths[None, :, None]
        ck = cache_k.at[l_idx, b_idx, h_idx, w_idx].set(new_k.astype(cache_k.dtype))
        cv = cache_v.at[l_idx, b_idx, h_idx, w_idx].set(new_v.astype(cache_v.dtype))
        return ck, cv

    kernel = functools.partial(_append_bf16_kernel, block_q=BQ, seq_len=S)

    def blkq(lens, b):
        # tile holding this row's write position (clamped if parked)
        return jnp.minimum(lens[b], S - 1) // BQ

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # lengths [Ba], cache row ids [Ba]
        grid=(Ba,),
        in_specs=[
            pl.BlockSpec((L, 1, Hkv, hd), lambda b, lens, ids: (0, b, 0, 0)),
            pl.BlockSpec((L, 1, Hkv, hd), lambda b, lens, ids: (0, b, 0, 0)),
            pl.BlockSpec(
                (L, 1, Hkv, BQ, hd), lambda b, lens, ids: (0, ids[b], 0, blkq(lens, b), 0)
            ),
            pl.BlockSpec(
                (L, 1, Hkv, BQ, hd), lambda b, lens, ids: (0, ids[b], 0, blkq(lens, b), 0)
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (L, 1, Hkv, BQ, hd), lambda b, lens, ids: (0, ids[b], 0, blkq(lens, b), 0)
            ),
            pl.BlockSpec(
                (L, 1, Hkv, BQ, hd), lambda b, lens, ids: (0, ids[b], 0, blkq(lens, b), 0)
            ),
        ],
    )
    ok, ov = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(cache_k.shape, cache_k.dtype),
            jax.ShapeDtypeStruct(cache_v.shape, cache_v.dtype),
        ],
        # operand indices include the prefetch scalars: lengths=0, ids=1,
        # nk=2, nv=3, ck=4, cv=5 → outputs 0..1
        input_output_aliases={4: 0, 5: 1},
        interpret=interp,
    )(
        lengths.astype(jnp.int32),
        rows,
        new_k,
        new_v,
        cache_k,
        cache_v,
    )
    return ok, ov


@functools.partial(jax.jit, static_argnames=("interpret",))
def decode_attention(
    q: jnp.ndarray,  # [B, Hkv, G, hd]
    cache_k: jnp.ndarray,  # [B, Hkv, S, hd]
    cache_v: jnp.ndarray,  # [B, Hkv, S, hd]
    lengths: jnp.ndarray,  # [B] int32 — current write position (inclusive)
    *,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Batched single-step attention. Returns [B, Hkv, G, hd].

    The caller has already written this step's K/V at `lengths[b]`; the
    kernel attends over positions ≤ lengths[b]. Whole-S tiles stream through
    VMEM once; for cache capacities beyond VMEM (≳16K positions at hd=128)
    the sequence-parallel ring path (parallel/ring.py) shards S instead.
    """
    B, Hkv, G, hd = q.shape
    S = cache_k.shape[2]
    interp = _interpret() if interpret is None else interpret

    kernel = functools.partial(_decode_attn_kernel, scale=hd**-0.5)
    return pl.pallas_call(
        kernel,
        grid=(B, Hkv),
        in_specs=[
            _smem_spec(),  # lengths [B]
            pl.BlockSpec((1, 1, G, hd), lambda b, h: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, hd), lambda b, h: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, hd), lambda b, h: (b, h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, G, hd), lambda b, h: (b, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, hd), q.dtype),
        interpret=interp,
    )(lengths.astype(jnp.int32), q, cache_k, cache_v)


# ---------------------------------------------------------------------------
# Ragged paged-native flash prefill (the chunked-prefill path of record)
# ---------------------------------------------------------------------------
#
# One fixed-shape packed dispatch replaces the bucketed path's per-(bucket,
# skey) executable zoo. A [T]-token buffer carries up to R rows' chunks
# back-to-back: row r occupies packed positions [offsets[r], offsets[r+1]);
# pads sit past offsets[R] with rowid == R and write position == S, so their
# cache scatters DROP (the engine's parked-slot OOB convention). Each kernel
# tiles q-blocks against
#
#   (a) the row's already-cached prefix, streamed block-indirect through the
#       PR 10 per-slot tables (arena identity homes < pool_base, shared
#       prefix pool rows >= pool_base — the same two-way `pl.when` descriptor
#       resolution as `_attend_q8_paged_kernel`), masked `k_pos < starts[r]`;
#   (b) the packed SELF segment from in-register K/V (exact bf16, even over
#       an int8 cache — the chunk path's current-token override generalized),
#       masked by segment equality + packed-index causal order.
#
# T and R are static; every descriptor (offsets, starts, tables) is data —
# one executable per (T, layout) serves every fill mix. Masks come from the
# row's segment BOUNDARIES (scalar-prefetch `offsets`, rows packed in
# ascending order), not per-token rowid vectors: boundary compares are plain
# 2-D iota-vs-scalar ops, which Mosaic vectorizes with no gather/relayout.
#
# Numerics mirror `llama_prefill_chunk_batch` / `mla_prefill_chunk_batch`:
# raw dots accumulate in f32 (int8 values are exact in every wider dtype),
# per-position dequant scales fold post-dot on the score AND value sides, and
# the attn scale applies to scores after dequant. The kernels use online
# softmax where the bucketed path takes one joint softmax — reductions
# associate differently, so outputs agree to bf16 rounding, not bitwise; the
# acceptance bar is greedy token identity (tests/test_kernel_parity.py).
#
# Sliding-window and softcap families are NOT covered — the engine gates
# those to the bucketed path (`GenerationEngine._ragged` eligibility).


def resolve_ragged_impl() -> str:
    """Implementation for the ragged chunked-prefill attention.

    env LLM_MCP_TPU_RAGGED_IMPL: auto (default) | kernel | xla.
    auto → the Pallas kernels on a TPU chip, the exact packed XLA fallback
    elsewhere (CPU serve; parity tests force `kernel` to exercise the
    kernels in interpret mode). This only picks HOW a ragged dispatch
    computes attention — whether ragged dispatch happens at all is the
    engine's TPU_RAGGED_PREFILL gate."""
    mode = os.environ.get("LLM_MCP_TPU_RAGGED_IMPL", "auto")
    if mode in ("kernel", "xla"):
        return mode
    return "kernel" if _on_tpu() else "xla"


def ragged_block_size(seq_len: int, block_tokens: int | None = None) -> int:
    """KV block size for the ragged kernels' past streams. Under physical
    paging it MUST equal the ledger's block_tokens (logical block j covers
    exactly table entry j); unpaged identity tables pick the largest
    MXU-friendly divisor of S."""
    if block_tokens:
        return block_tokens
    for bs in (256, 128, 64, 32):
        if seq_len % bs == 0 and bs <= seq_len:
            return bs
    return seq_len


def ragged_prefill_max_tokens(
    head_dim: int, n_kv_heads: int, *, latent: int = 0, rope_dim: int = 0
) -> int:
    """Largest packed-token capacity T the ragged kernels can hold in VMEM.

    The self segment keeps the whole chunk's K/V (GQA: 2·Hkv·hd bf16 per
    token; MLA: latent+rope bf16 per token) resident across q-tiles; the
    past stream is double-buffered blocks (T-independent). 10 MB of the
    ~16 MB budget bounds T, leaving headroom for q/out tiles, f32 score
    tiles, and the MLA pre-gathered rope/scale rows."""
    budget = 10 * 1024 * 1024
    if latent:
        per_tok = 2 * (latent + rope_dim)
    else:
        per_tok = 2 * 2 * n_kv_heads * head_dim
    return max(256, budget // per_tok)


def _seg_of(offs_ref, idx, n_rows: int):
    """Descriptor row of packed index `idx` by counting crossed boundaries
    (rows are packed contiguously ascending; pads land in segment n_rows)."""
    seg = jnp.zeros(idx.shape, jnp.int32)
    for r in range(1, n_rows + 1):
        seg = seg + (idx >= offs_ref[r]).astype(jnp.int32)
    return seg


def _ragged_prefill_bf16_kernel(
    li_ref,  # [1] int32 (scalar prefetch) — layer index
    offs_ref,  # [R+1] int32 (scalar prefetch) — packed row boundaries
    starts_ref,  # [R] int32 (scalar prefetch) — cached-prefix length per row
    tbl_ref,  # [R * nbs] int32 (scalar prefetch) — flattened block tables
    q_ref,  # [Hkv, BQ, G, hd] VMEM — this tile's post-rope queries
    ks_ref,  # [Hkv, T, hd] VMEM — the chunk's own post-rope keys (packed)
    vs_ref,  # [Hkv, T, hd] VMEM
    ck_hbm,  # [L, B, Hkv, S, hd] ANY — arena K (identity homes)
    cv_hbm,  # ANY — arena V
    pk_hbm,  # [L, PXB, Hkv, bt, hd] ANY — prefix pool K
    pv_hbm,  # ANY — prefix pool V
    o_ref,  # [Hkv, BQ, G, hd] VMEM out
    kbuf,  # VMEM scratch [2, Hkv, BS, hd] (double buffer)
    vbuf,
    sems,  # DMA semaphores [2, 2]
    *,
    scale: float,
    block_s: int,
    seq_len: int,
    n_rows: int,
):
    """Ragged flash prefill over the split bf16 GQA cache: per packed q-tile,
    one double-buffered block-indirect K/V stream per descriptor row (past),
    then causal packed self tiles, all folded into one online softmax."""
    qi = pl.program_id(0)
    li = li_ref[0]
    BS = block_s
    Hkv, BQ, G, hd = q_ref.shape
    nbs = seq_len // BS
    pool_base = ck_hbm.shape[1] * nbs
    t0 = qi * BQ

    q = q_ref[...].astype(jnp.float32)  # [Hkv, BQ, G, hd]
    t_idx = t0 + jax.lax.broadcasted_iota(jnp.int32, (BQ, 1), 0)  # packed idx

    acc = jnp.zeros((Hkv, BQ, G, hd), jnp.float32)
    m = jnp.full((Hkv, BQ, G, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((Hkv, BQ, G, 1), jnp.float32)

    # ---- past segment: block-indirect stream per row with cached prefix
    for r in range(n_rows):
        w = starts_ref[r]
        lo = offs_ref[r]
        hi = offs_ref[r + 1]
        # skip rows with no tokens in this tile or no cached prefix
        use = (hi > lo) & (lo < t0 + BQ) & (hi > t0) & (w > 0)
        nblk = jnp.where(use, jnp.minimum((w + BS - 1) // BS, nbs), 0)

        def issue(j, slot, op, r=r):
            phys = tbl_ref[r * nbs + j]
            ina = phys < pool_base

            @pl.when(ina)
            def _arena():
                arow = phys // nbs
                aoff = (phys % nbs) * BS
                for c in (
                    pltpu.make_async_copy(
                        ck_hbm.at[li, arow, :, pl.ds(aoff, BS), :],
                        kbuf.at[slot],
                        sems.at[slot, 0],
                    ),
                    pltpu.make_async_copy(
                        cv_hbm.at[li, arow, :, pl.ds(aoff, BS), :],
                        vbuf.at[slot],
                        sems.at[slot, 1],
                    ),
                ):
                    getattr(c, op)()

            @pl.when(jnp.logical_not(ina))
            def _pool():
                prow = phys - pool_base
                for c in (
                    pltpu.make_async_copy(
                        pk_hbm.at[li, prow], kbuf.at[slot], sems.at[slot, 0]
                    ),
                    pltpu.make_async_copy(
                        pv_hbm.at[li, prow], vbuf.at[slot], sems.at[slot, 1]
                    ),
                ):
                    getattr(c, op)()

        @pl.when(nblk > 0)
        def _warm(issue=issue):
            issue(0, 0, "start")

        sel_q = (t_idx >= lo) & (t_idx < hi)  # [BQ, 1]

        def body(j, carry, issue=issue, sel_q=sel_q, w=w, nblk=nblk):
            acc, m, l = carry
            slot = jax.lax.rem(j, 2)

            @pl.when(j + 1 < nblk)
            def _pf():
                issue(j + 1, 1 - slot, "start")

            issue(j, slot, "wait")
            k = kbuf[slot].astype(jnp.float32)  # [Hkv, BS, hd]
            v = vbuf[slot].astype(jnp.float32)
            s = (
                jax.lax.dot_general(
                    q, k, (((3,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [Hkv, BQ, G, BS]
            k_pos = j * BS + jax.lax.broadcasted_iota(jnp.int32, (1, BS), 1)
            mask = (sel_q & (k_pos < w))[None, :, None, :]
            s = jnp.where(mask, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * alpha + jax.lax.dot_general(
                p, v, (((3,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            return acc, m_new, l

        acc, m, l = jax.lax.fori_loop(0, nblk, body, (acc, m, l))

    # ---- self segment: causal packed tiles, segment-equality masked
    seg_q = _seg_of(offs_ref, t_idx, n_rows)  # [BQ, 1]

    def sbody(tb, carry):
        acc, m, l = carry
        k = ks_ref[:, pl.ds(tb * BQ, BQ), :].astype(jnp.float32)
        v = vs_ref[:, pl.ds(tb * BQ, BQ), :].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k, (((3,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Hkv, BQ, G, BQk]
        u_idx = tb * BQ + jax.lax.broadcasted_iota(jnp.int32, (1, BQ), 1)
        seg_k = _seg_of(offs_ref, u_idx, n_rows)  # [1, BQk]
        mask = ((seg_q == seg_k) & (u_idx <= t_idx))[None, :, None, :]
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((3,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l

    acc, m, l = jax.lax.fori_loop(0, qi + 1, sbody, (acc, m, l))
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    o_ref[...] = out.astype(o_ref.dtype)


def _ragged_prefill_q8_kernel(
    li_ref,  # [1] int32 (scalar prefetch)
    offs_ref,  # [R+1] int32 (scalar prefetch)
    starts_ref,  # [R] int32 (scalar prefetch)
    tbl_ref,  # [R * nbs] int32 (scalar prefetch)
    q_ref,  # [Hkv, BQ, G, hd] VMEM — post-rope queries (bf16)
    ks_ref,  # [Hkv, T, hd] VMEM — self keys, exact bf16
    vs_ref,  # [Hkv, T, hd] VMEM
    srow_ref,  # [R, 2*Hkv, S] VMEM — pre-gathered plain dequant scales
    pay_hbm,  # [L, B, 2*Hkv + p, S, hd] int8 ANY — fused arena payload
    pool_pay_hbm,  # [L, PXB, 2*Hkv + p, bt, hd] int8 ANY — prefix pool
    o_ref,  # [Hkv, BQ, G, hd] VMEM out
    pay_buf,  # VMEM scratch [2, 2*Hkv, BS, hd] int8 (double buffer)
    sems,  # DMA semaphores [2, 1]
    *,
    scale: float,
    block_s: int,
    seq_len: int,
    n_rows: int,
):
    """Ragged flash prefill over the FUSED int8 GQA cache. One payload DMA
    per past block (K and V heads ride the same copy — the PR 7 one-DMA
    property); the packed-scale pseudo-head is never streamed — per-row
    plain scales arrive PRE-GATHERED whole-S in VMEM (`paged_gather` on the
    "s" plane), dodging the narrow scale-row DMAs Mosaic rejects (see
    `_attend_q8_mla_blocked_kernel`). Dequant folds post-dot on score and
    value sides; the self segment stays exact bf16 from registers."""
    qi = pl.program_id(0)
    li = li_ref[0]
    BS = block_s
    Hkv, BQ, G, hd = q_ref.shape
    nbs = seq_len // BS
    pool_base = pay_hbm.shape[1] * nbs
    t0 = qi * BQ

    q = q_ref[...].astype(jnp.float32)
    t_idx = t0 + jax.lax.broadcasted_iota(jnp.int32, (BQ, 1), 0)

    acc = jnp.zeros((Hkv, BQ, G, hd), jnp.float32)
    m = jnp.full((Hkv, BQ, G, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((Hkv, BQ, G, 1), jnp.float32)

    for r in range(n_rows):
        w = starts_ref[r]
        lo = offs_ref[r]
        hi = offs_ref[r + 1]
        use = (hi > lo) & (lo < t0 + BQ) & (hi > t0) & (w > 0)
        nblk = jnp.where(use, jnp.minimum((w + BS - 1) // BS, nbs), 0)

        def issue(j, slot, op, r=r):
            phys = tbl_ref[r * nbs + j]
            ina = phys < pool_base

            @pl.when(ina)
            def _arena():
                arow = phys // nbs
                aoff = (phys % nbs) * BS
                getattr(
                    pltpu.make_async_copy(
                        pay_hbm.at[li, arow, pl.ds(0, 2 * Hkv), pl.ds(aoff, BS), :],
                        pay_buf.at[slot],
                        sems.at[slot, 0],
                    ),
                    op,
                )()

            @pl.when(jnp.logical_not(ina))
            def _pool():
                prow = phys - pool_base
                getattr(
                    pltpu.make_async_copy(
                        pool_pay_hbm.at[li, prow, pl.ds(0, 2 * Hkv)],
                        pay_buf.at[slot],
                        sems.at[slot, 0],
                    ),
                    op,
                )()

        @pl.when(nblk > 0)
        def _warm(issue=issue):
            issue(0, 0, "start")

        sel_q = (t_idx >= lo) & (t_idx < hi)

        def body(j, carry, issue=issue, sel_q=sel_q, w=w, nblk=nblk, r=r):
            acc, m, l = carry
            slot = jax.lax.rem(j, 2)

            @pl.when(j + 1 < nblk)
            def _pf():
                issue(j + 1, 1 - slot, "start")

            issue(j, slot, "wait")
            buf = pay_buf[slot]  # [2*Hkv, BS, hd] int8
            k = buf[:Hkv].astype(jnp.float32)
            v = buf[Hkv:].astype(jnp.float32)
            ss = srow_ref[r, :, pl.ds(j * BS, BS)].astype(jnp.float32)  # [2Hkv,BS]
            kss, vss = ss[:Hkv], ss[Hkv:]
            s = (
                jax.lax.dot_general(
                    q, k, (((3,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
                * kss[:, None, None, :]
                * scale
            )
            k_pos = j * BS + jax.lax.broadcasted_iota(jnp.int32, (1, BS), 1)
            mask = (sel_q & (k_pos < w))[None, :, None, :]
            s = jnp.where(mask, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * alpha + jax.lax.dot_general(
                p * vss[:, None, None, :], v, (((3,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            return acc, m_new, l

        acc, m, l = jax.lax.fori_loop(0, nblk, body, (acc, m, l))

    seg_q = _seg_of(offs_ref, t_idx, n_rows)

    def sbody(tb, carry):
        acc, m, l = carry
        k = ks_ref[:, pl.ds(tb * BQ, BQ), :].astype(jnp.float32)
        v = vs_ref[:, pl.ds(tb * BQ, BQ), :].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k, (((3,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        u_idx = tb * BQ + jax.lax.broadcasted_iota(jnp.int32, (1, BQ), 1)
        seg_k = _seg_of(offs_ref, u_idx, n_rows)
        mask = ((seg_q == seg_k) & (u_idx <= t_idx))[None, :, None, :]
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((3,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l

    acc, m, l = jax.lax.fori_loop(0, qi + 1, sbody, (acc, m, l))
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    o_ref[...] = out.astype(o_ref.dtype)


def _ragged_prefill_mla_kernel(
    li_ref,  # [1] int32 (scalar prefetch)
    offs_ref,  # [R+1] int32 (scalar prefetch)
    starts_ref,  # [R] int32 (scalar prefetch)
    tbl_ref,  # [R * nbs] int32 (scalar prefetch)
    qt_ref,  # [BQ, H, Rl] VMEM — absorbed latent queries (q_nope @ W_uk)
    qr_ref,  # [BQ, H, dr] VMEM — post-rope rope queries
    cs_ref,  # [T, Rl] VMEM — the chunk's own latents, exact bf16
    krs_ref,  # [T, dr] VMEM — the chunk's own post-rope rope keys
    rop_ref,  # [R, S, dr] VMEM — pre-gathered cached rope rows (native dtype)
    ls_ref,  # [R, 1, S] VMEM — latent dequant scales (ones when bf16)
    rs_ref,  # [R, 1, S] VMEM — rope dequant scales (ones when bf16)
    lat_hbm,  # [L, B, 1, S, Rl] ANY — latent arena (int8 or bf16)
    pool_lat,  # [L, PXB, 1, bt, Rl] ANY — latent prefix pool
    o_ref,  # [BQ, H, Rl] VMEM out — attended latent context
    lbuf,  # VMEM scratch [2, BS, Rl] (double buffer)
    sems,  # DMA semaphores [2, 1]
    *,
    scale: float,
    block_s: int,
    seq_len: int,
    n_rows: int,
):
    """Ragged flash prefill over the MLA latent cache, absorbed form: scores
    land directly on cached latents (q_nope pre-folded through W_uk), the
    value side re-expands outside the kernel. One static `quantized`-free
    body covers bf16 AND int8 latents: blocks stream in the cache's native
    dtype and dequant scales (ones for bf16 — exact multiply) fold post-dot.
    Rope rows + scales arrive PRE-GATHERED whole-S (`paged_gather`): the
    per-block [BS, dr] rope slices are exactly the narrow DMAs Mosaic
    rejects in the MLA decode kernels, so only the [BS, Rl] latent payload
    streams block-indirect."""
    qi = pl.program_id(0)
    li = li_ref[0]
    BS = block_s
    BQ, H, Rl = qt_ref.shape
    nbs = seq_len // BS
    pool_base = lat_hbm.shape[1] * nbs
    t0 = qi * BQ

    qt = qt_ref[...].astype(jnp.float32)  # [BQ, H, Rl]
    qr = qr_ref[...].astype(jnp.float32)  # [BQ, H, dr]
    t_idx = t0 + jax.lax.broadcasted_iota(jnp.int32, (BQ, 1), 0)

    acc = jnp.zeros((BQ, H, Rl), jnp.float32)
    m = jnp.full((BQ, H, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((BQ, H, 1), jnp.float32)

    for r in range(n_rows):
        w = starts_ref[r]
        lo = offs_ref[r]
        hi = offs_ref[r + 1]
        use = (hi > lo) & (lo < t0 + BQ) & (hi > t0) & (w > 0)
        nblk = jnp.where(use, jnp.minimum((w + BS - 1) // BS, nbs), 0)

        def issue(j, slot, op, r=r):
            phys = tbl_ref[r * nbs + j]
            ina = phys < pool_base

            @pl.when(ina)
            def _arena():
                arow = phys // nbs
                aoff = (phys % nbs) * BS
                getattr(
                    pltpu.make_async_copy(
                        lat_hbm.at[li, arow, 0, pl.ds(aoff, BS), :],
                        lbuf.at[slot],
                        sems.at[slot, 0],
                    ),
                    op,
                )()

            @pl.when(jnp.logical_not(ina))
            def _pool():
                prow = phys - pool_base
                getattr(
                    pltpu.make_async_copy(
                        pool_lat.at[li, prow, 0], lbuf.at[slot], sems.at[slot, 0]
                    ),
                    op,
                )()

        @pl.when(nblk > 0)
        def _warm(issue=issue):
            issue(0, 0, "start")

        sel_q = (t_idx >= lo) & (t_idx < hi)  # [BQ, 1]

        def body(j, carry, issue=issue, sel_q=sel_q, w=w, nblk=nblk, r=r):
            acc, m, l = carry
            slot = jax.lax.rem(j, 2)

            @pl.when(j + 1 < nblk)
            def _pf():
                issue(j + 1, 1 - slot, "start")

            issue(j, slot, "wait")
            lat = lbuf[slot].astype(jnp.float32)  # [BS, Rl]
            rop = rop_ref[r, pl.ds(j * BS, BS), :].astype(jnp.float32)  # [BS,dr]
            lsb = ls_ref[r, :, pl.ds(j * BS, BS)].astype(jnp.float32)  # [1, BS]
            rsb = rs_ref[r, :, pl.ds(j * BS, BS)].astype(jnp.float32)
            s = (
                jax.lax.dot_general(
                    qt, lat, (((2,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * lsb[:, None, :]
                + jax.lax.dot_general(
                    qr, rop, (((2,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * rsb[:, None, :]
            ) * scale  # [BQ, H, BS]
            k_pos = j * BS + jax.lax.broadcasted_iota(jnp.int32, (1, BS), 1)
            mask = (sel_q & (k_pos < w))[:, None, :]
            s = jnp.where(mask, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * alpha + jax.lax.dot_general(
                p * lsb[:, None, :], lat, (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return acc, m_new, l

        acc, m, l = jax.lax.fori_loop(0, nblk, body, (acc, m, l))

    seg_q = _seg_of(offs_ref, t_idx, n_rows)

    def sbody(tb, carry):
        acc, m, l = carry
        c = cs_ref[pl.ds(tb * BQ, BQ), :].astype(jnp.float32)  # [BQk, Rl]
        kr = krs_ref[pl.ds(tb * BQ, BQ), :].astype(jnp.float32)  # [BQk, dr]
        s = (
            jax.lax.dot_general(
                qt, c, (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            + jax.lax.dot_general(
                qr, kr, (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        ) * scale  # [BQ, H, BQk]
        u_idx = tb * BQ + jax.lax.broadcasted_iota(jnp.int32, (1, BQ), 1)
        seg_k = _seg_of(offs_ref, u_idx, n_rows)
        mask = ((seg_q == seg_k) & (u_idx <= t_idx))[:, None, :]
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, c, (((2,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l

    acc, m, l = jax.lax.fori_loop(0, qi + 1, sbody, (acc, m, l))
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    o_ref[...] = out.astype(o_ref.dtype)


def _ragged_attend_gqa_fallback(
    q, k_self, v_self, krows, vrows, ksr, vsr, rowids, starts, scale
):
    """Exact packed mirror of `llama_prefill_chunk_batch`'s attention math
    (joint softmax over [past | self], bf16 dots, post-dot dequant) — the
    CPU/XLA arm of the ragged dispatchers and the reference the kernels are
    parity-tested against. Past rows arrive pre-gathered per descriptor row
    ([R, Hkv, Sk, hd]); a static loop over the R rows selects each token's
    row without a [T, Sk, hd] gather (memory mirrors the bucketed form).

    q [T, Hkv, G, hd] · k_self/v_self [T, Hkv, hd] · ksr/vsr [R, Hkv, Sk]
    (None for bf16) · rowids [T] (pads = R) · starts [R] → [T, Hkv, G, hd].
    """
    T, Hkv, G, hd = q.shape
    R, _, Sk, _ = krows.shape
    neg = jnp.float32(NEG_INF)
    rid = rowids.astype(jnp.int32)
    t_idx = jnp.arange(T, dtype=jnp.int32)
    key_pos = jnp.arange(Sk, dtype=jnp.int32)

    s_past = jnp.full((Hkv, G, T, Sk), neg, jnp.float32)
    for r in range(R):
        sr = jnp.einsum(
            "thgd,hsd->hgts", q, krows[r].astype(q.dtype)
        ).astype(jnp.float32)
        if ksr is not None:
            sr = sr * ksr[r].astype(jnp.float32)[:, None, None, :]
        s_past = jnp.where((rid == r)[None, None, :, None], sr, s_past)
    s_past = s_past * scale
    start_t = starts[jnp.clip(rid, 0, R - 1)]  # [T]
    pm = (key_pos[None, :] < start_t[:, None]) & (rid < R)[:, None]
    s_past = jnp.where(pm[None, None], s_past, neg)

    s_self = jnp.einsum("thgd,uhd->hgtu", q, k_self).astype(jnp.float32) * scale
    sm = (rid[None, :] == rid[:, None]) & (t_idx[None, :] <= t_idx[:, None])
    s_self = jnp.where(sm[None, None], s_self, neg)

    s = jnp.concatenate([s_past, s_self], axis=-1)  # [Hkv, G, T, Sk+T]
    probs = jax.nn.softmax(s, axis=-1)
    p_past, p_self = probs[..., :Sk], probs[..., Sk:]
    ctx = jnp.einsum("hgtu,uhd->thgd", p_self.astype(q.dtype), v_self)
    for r in range(R):
        pr = p_past
        if vsr is not None:
            pr = pr * vsr[r].astype(jnp.float32)[:, None, None, :]
        cr = jnp.einsum("hgts,hsd->thgd", pr.astype(q.dtype), vrows[r].astype(q.dtype))
        ctx = ctx + jnp.where((rid == r)[:, None, None, None], cr, jnp.zeros_like(cr))
    return ctx.astype(q.dtype)


def _ragged_attend_mla_fallback(
    qt, qr, c_self, kr_self, lat, rop, ls, rs, rowids, starts, scale
):
    """Exact packed mirror of `mla_prefill_chunk_batch`'s attention math —
    the XLA arm of `ragged_prefill_attend_mla` and the kernels' parity
    reference. lat/rop [R, Sk, ·] pre-gathered; ls/rs [R, Sk] f32 dequant
    scales or None (bf16). Returns attended latent context [T, H, Rl]."""
    T, H, Rl = qt.shape
    R, Sk, _ = lat.shape
    neg = jnp.float32(NEG_INF)
    rid = rowids.astype(jnp.int32)
    t_idx = jnp.arange(T, dtype=jnp.int32)
    key_pos = jnp.arange(Sk, dtype=jnp.int32)

    s_past = jnp.full((H, T, Sk), neg, jnp.float32)
    for r in range(R):
        sr = jnp.einsum("thr,sr->hts", qt, lat[r].astype(qt.dtype)).astype(
            jnp.float32
        )
        rr = jnp.einsum("thd,sd->hts", qr, rop[r].astype(qr.dtype)).astype(
            jnp.float32
        )
        if ls is not None:
            sr = sr * ls[r][None, None, :]
            rr = rr * rs[r][None, None, :]
        s_past = jnp.where((rid == r)[None, :, None], sr + rr, s_past)
    s_past = s_past * scale
    start_t = starts[jnp.clip(rid, 0, R - 1)]
    pm = (key_pos[None, :] < start_t[:, None]) & (rid < R)[:, None]
    s_past = jnp.where(pm[None], s_past, neg)

    s_self = (
        jnp.einsum("thr,ur->htu", qt, c_self)
        + jnp.einsum("thd,ud->htu", qr, kr_self)
    ).astype(jnp.float32) * scale
    sm = (rid[None, :] == rid[:, None]) & (t_idx[None, :] <= t_idx[:, None])
    s_self = jnp.where(sm[None], s_self, neg)

    s = jnp.concatenate([s_past, s_self], axis=-1)  # [H, T, Sk+T]
    probs = jax.nn.softmax(s, axis=-1)
    p_past, p_self = probs[..., :Sk], probs[..., Sk:]
    ctx = jnp.einsum("htu,ur->thr", p_self.astype(qt.dtype), c_self)
    for r in range(R):
        pr = p_past * ls[r][None, None, :] if ls is not None else p_past
        cr = jnp.einsum("hts,sr->thr", pr.astype(qt.dtype), lat[r].astype(qt.dtype))
        ctx = ctx + jnp.where((rid == r)[:, None, None], cr, jnp.zeros_like(cr))
    return ctx.astype(qt.dtype)


def _ragged_tables(slots, S, BS, block_tables):
    """(tbl [R, nbs], nbs, paged?) — the per-row block tables the kernels
    stream through: the PR 10 ledger tables gathered to the descriptor rows,
    or identity tables (phys = slot·nbs + j, always arena) when unpaged."""
    slots = jnp.asarray(slots, jnp.int32)
    if block_tables is not None:
        return jnp.take(block_tables, slots, axis=0), block_tables.shape[1], True
    nbs = S // BS
    tbl = slots[:, None] * nbs + jnp.arange(nbs, dtype=jnp.int32)[None, :]
    return tbl, nbs, False


def ragged_prefill_attend_bf16(
    q: jnp.ndarray,  # [T, Hkv, G, hd] post-rope queries (packed)
    k_self: jnp.ndarray,  # [T, Hkv, hd] the chunk's own post-rope keys
    v_self: jnp.ndarray,  # [T, Hkv, hd]
    cache_k: jnp.ndarray,  # [L, B, Hkv, S, hd]
    cache_v: jnp.ndarray,
    layer,  # traced int32 scalar
    rowids: jnp.ndarray,  # [T] int32 — descriptor row per token (pads = R)
    offsets: jnp.ndarray,  # [R+1] int32 — packed row boundaries
    slots: jnp.ndarray,  # [R] int32
    starts: jnp.ndarray,  # [R] int32 — cached-prefix length per row
    *,
    scale: float = 0.0,
    skey: int = 0,  # STATIC past bound for the XLA arm (0 = whole S)
    block_tables=None,  # [max_slots, nbs] ledger tables (None = unpaged)
    pool_k=None,  # [L, PXB, Hkv, bt, hd] prefix pool
    pool_v=None,
    impl: str | None = None,
    interpret: bool | None = None,
    block_q: int = 128,
) -> jnp.ndarray:
    """Ragged chunked-prefill attention over the split bf16 GQA cache.
    Returns [T, Hkv, G, hd] attended context for the packed chunk."""
    T, Hkv, G, hd = q.shape
    L, B, _, S, _ = cache_k.shape
    R = slots.shape[0]
    sc = scale or hd**-0.5
    starts = jnp.asarray(starts, jnp.int32)
    use_kernel = (impl or resolve_ragged_impl()) == "kernel" and _HAS_PLTPU

    if not use_kernel:
        Sk = min(skey, S) if skey else S
        ck_l = jax.lax.dynamic_index_in_dim(cache_k, layer, 0, keepdims=False)
        cv_l = jax.lax.dynamic_index_in_dim(cache_v, layer, 0, keepdims=False)
        slots_i = jnp.asarray(slots, jnp.int32)
        if block_tables is not None:
            nbs_full = block_tables.shape[1]
            bt = S // nbs_full
            nsel = max(1, -(-Sk // bt))
            tbl = jnp.take(block_tables, slots_i, axis=0)[:, :nsel]
            pk_l = jax.lax.dynamic_index_in_dim(pool_k, layer, 0, keepdims=False)
            pv_l = jax.lax.dynamic_index_in_dim(pool_v, layer, 0, keepdims=False)
            krows = paged_gather(ck_l, pk_l, tbl, nbs=nbs_full)[:, :, :Sk]
            vrows = paged_gather(cv_l, pv_l, tbl, nbs=nbs_full)[:, :, :Sk]
        else:
            krows = jnp.take(ck_l, slots_i, axis=0)[:, :, :Sk]
            vrows = jnp.take(cv_l, slots_i, axis=0)[:, :, :Sk]
        return _ragged_attend_gqa_fallback(
            q, k_self, v_self, krows, vrows, None, None, rowids, starts, sc
        )

    interp = _interpret() if interpret is None else interpret
    bt = None if block_tables is None else S // block_tables.shape[1]
    BS = ragged_block_size(S, bt)
    tbl, nbs, paged = _ragged_tables(slots, S, BS, block_tables)
    if paged:
        pk, pv = pool_k, pool_v
    else:
        pk = jnp.zeros((L, 1, Hkv, BS, hd), cache_k.dtype)
        pv = jnp.zeros((L, 1, Hkv, BS, hd), cache_v.dtype)
    BQ = min(block_q, T)
    assert T % BQ == 0, (T, BQ)
    kernel = functools.partial(
        _ragged_prefill_bf16_kernel, scale=sc, block_s=BS, seq_len=S, n_rows=R
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,  # li [1], offsets [R+1], starts [R], tbl [R*nbs]
        grid=(T // BQ,),
        in_specs=[
            pl.BlockSpec((Hkv, BQ, G, hd), lambda qi, li, of, st, tb: (0, qi, 0, 0)),
            pl.BlockSpec((Hkv, T, hd), lambda qi, li, of, st, tb: (0, 0, 0)),
            pl.BlockSpec((Hkv, T, hd), lambda qi, li, of, st, tb: (0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),  # arena K
            pl.BlockSpec(memory_space=pl.ANY),  # arena V
            pl.BlockSpec(memory_space=pl.ANY),  # pool K
            pl.BlockSpec(memory_space=pl.ANY),  # pool V
        ],
        out_specs=pl.BlockSpec(
            (Hkv, BQ, G, hd), lambda qi, li, of, st, tb: (0, qi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, Hkv, BS, hd), cache_k.dtype),
            pltpu.VMEM((2, Hkv, BS, hd), cache_v.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Hkv, T, G, hd), q.dtype),
        interpret=interp,
    )(
        jnp.reshape(jnp.asarray(layer, jnp.int32), (1,)),
        jnp.asarray(offsets, jnp.int32),
        starts,
        tbl.reshape(-1).astype(jnp.int32),
        q.transpose(1, 0, 2, 3),
        k_self.transpose(1, 0, 2),
        v_self.transpose(1, 0, 2),
        cache_k,
        cache_v,
        pk,
        pv,
    )
    return out.transpose(1, 0, 2, 3)


def ragged_prefill_attend_q8(
    q: jnp.ndarray,  # [T, Hkv, G, hd] post-rope queries (packed)
    k_self: jnp.ndarray,  # [T, Hkv, hd] exact bf16 self keys
    v_self: jnp.ndarray,
    cache_k: dict,  # FUSED int8 cache {"q": [L,B,2Hkv+p,S,hd], "s": [L,B,2Hkv,S]}
    layer,
    rowids: jnp.ndarray,
    offsets: jnp.ndarray,
    slots: jnp.ndarray,
    starts: jnp.ndarray,
    *,
    scale: float = 0.0,
    skey: int = 0,
    block_tables=None,
    pool=None,  # {"q", "s"} prefix pool (paged["k"])
    impl: str | None = None,
    interpret: bool | None = None,
    block_q: int = 128,
) -> jnp.ndarray:
    """Ragged chunked-prefill attention over the FUSED int8 GQA cache.
    Returns [T, Hkv, G, hd]."""
    T, Hkv, G, hd = q.shape
    L, B, _, S, _ = cache_k["q"].shape
    R = slots.shape[0]
    sc = scale or hd**-0.5
    starts = jnp.asarray(starts, jnp.int32)
    slots_i = jnp.asarray(slots, jnp.int32)
    use_kernel = (impl or resolve_ragged_impl()) == "kernel" and _HAS_PLTPU

    if not use_kernel:
        Sk = min(skey, S) if skey else S
        pay_l = jax.lax.dynamic_index_in_dim(cache_k["q"], layer, 0, keepdims=False)
        ss_l = jax.lax.dynamic_index_in_dim(cache_k["s"], layer, 0, keepdims=False)
        if block_tables is not None:
            nbs_full = block_tables.shape[1]
            bt = S // nbs_full
            nsel = max(1, -(-Sk // bt))
            tbl = jnp.take(block_tables, slots_i, axis=0)[:, :nsel]
            pp_l = jax.lax.dynamic_index_in_dim(pool["q"], layer, 0, keepdims=False)
            ps_l = jax.lax.dynamic_index_in_dim(pool["s"], layer, 0, keepdims=False)
            pays = paged_gather(pay_l, pp_l, tbl, nbs=nbs_full)[:, : 2 * Hkv, :Sk]
            srows = paged_gather(ss_l, ps_l, tbl, nbs=nbs_full)[:, : 2 * Hkv, :Sk]
        else:
            pays = jnp.take(pay_l, slots_i, axis=0)[:, : 2 * Hkv, :Sk]
            srows = jnp.take(ss_l, slots_i, axis=0)[:, : 2 * Hkv, :Sk]
        return _ragged_attend_gqa_fallback(
            q,
            k_self,
            v_self,
            pays[:, :Hkv],
            pays[:, Hkv:],
            srows[:, :Hkv],
            srows[:, Hkv:],
            rowids,
            starts,
            sc,
        )

    interp = _interpret() if interpret is None else interpret
    bt = None if block_tables is None else S // block_tables.shape[1]
    BS = ragged_block_size(S, bt)
    tbl, nbs, paged_ = _ragged_tables(slots, S, BS, block_tables)
    # plain scales pre-gathered whole-S through the same tables the payload
    # streams through — the scale rows must come from the SAME physical
    # blocks (pool rows for a pinned prefix), not the arena slot rows
    ss_l = jax.lax.dynamic_index_in_dim(cache_k["s"], layer, 0, keepdims=False)
    if paged_:
        ps_l = jax.lax.dynamic_index_in_dim(pool["s"], layer, 0, keepdims=False)
        srows = paged_gather(ss_l, ps_l, jnp.take(block_tables, slots_i, 0))
        pp = pool["q"]
    else:
        srows = jnp.take(ss_l, slots_i, axis=0)
        pp = jnp.zeros((L, 1, cache_k["q"].shape[2], BS, hd), jnp.int8)
    BQ = min(block_q, T)
    assert T % BQ == 0, (T, BQ)
    kernel = functools.partial(
        _ragged_prefill_q8_kernel, scale=sc, block_s=BS, seq_len=S, n_rows=R
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(T // BQ,),
        in_specs=[
            pl.BlockSpec((Hkv, BQ, G, hd), lambda qi, li, of, st, tb: (0, qi, 0, 0)),
            pl.BlockSpec((Hkv, T, hd), lambda qi, li, of, st, tb: (0, 0, 0)),
            pl.BlockSpec((Hkv, T, hd), lambda qi, li, of, st, tb: (0, 0, 0)),
            pl.BlockSpec(
                (R, 2 * Hkv, S), lambda qi, li, of, st, tb: (0, 0, 0)
            ),  # scales
            pl.BlockSpec(memory_space=pl.ANY),  # fused arena payload
            pl.BlockSpec(memory_space=pl.ANY),  # fused pool payload
        ],
        out_specs=pl.BlockSpec(
            (Hkv, BQ, G, hd), lambda qi, li, of, st, tb: (0, qi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 2 * Hkv, BS, hd), jnp.int8),
            pltpu.SemaphoreType.DMA((2, 1)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Hkv, T, G, hd), q.dtype),
        interpret=interp,
    )(
        jnp.reshape(jnp.asarray(layer, jnp.int32), (1,)),
        jnp.asarray(offsets, jnp.int32),
        starts,
        tbl.reshape(-1).astype(jnp.int32),
        q.transpose(1, 0, 2, 3),
        k_self.transpose(1, 0, 2),
        v_self.transpose(1, 0, 2),
        srows,
        cache_k["q"],
        pp,
    )
    return out.transpose(1, 0, 2, 3)


def ragged_prefill_attend_mla(
    qt: jnp.ndarray,  # [T, H, Rl] absorbed latent queries
    qr: jnp.ndarray,  # [T, H, dr] post-rope rope queries
    c_self: jnp.ndarray,  # [T, Rl] the chunk's own latents (exact bf16)
    kr_self: jnp.ndarray,  # [T, dr] the chunk's own post-rope rope keys
    cache_c,  # [L, B, 1, S, Rl] latents or int8 {"q","s"}
    cache_r,  # [L, B, 1, S, dr] rope keys or int8 {"q","s"}
    layer,
    rowids: jnp.ndarray,
    offsets: jnp.ndarray,
    slots: jnp.ndarray,
    starts: jnp.ndarray,
    *,
    scale: float,
    skey: int = 0,
    block_tables=None,
    pool_c=None,  # paged["k"] — latent prefix pool (array or {"q","s"})
    pool_r=None,  # paged["v"] — rope prefix pool
    impl: str | None = None,
    interpret: bool | None = None,
    block_q: int = 128,
) -> jnp.ndarray:
    """Ragged chunked-prefill attention over the MLA latent cache (absorbed
    form, bf16 or int8). Returns attended latent context [T, H, Rl] — the
    caller re-expands through W_uv."""
    quantized = isinstance(cache_c, dict)
    lat_all = cache_c["q"] if quantized else cache_c
    rop_all = cache_r["q"] if quantized else cache_r
    L, B, _, S, Rl = lat_all.shape
    dr = rop_all.shape[-1]
    T = qt.shape[0]
    R = slots.shape[0]
    starts = jnp.asarray(starts, jnp.int32)
    slots_i = jnp.asarray(slots, jnp.int32)
    use_kernel = (impl or resolve_ragged_impl()) == "kernel" and _HAS_PLTPU

    def rows_of(cache_full, pool_full, bound):
        """Layer-select + per-row gather of a cache plane, bounded to the
        first `bound` positions (block-rounded under paging)."""
        plane = jax.lax.dynamic_index_in_dim(cache_full, layer, 0, keepdims=False)
        if block_tables is not None:
            nbs_full = block_tables.shape[1]
            bt = S // nbs_full
            nsel = max(1, -(-bound // bt))
            pool_plane = jax.lax.dynamic_index_in_dim(
                pool_full, layer, 0, keepdims=False
            )
            tbl = jnp.take(block_tables, slots_i, axis=0)[:, :nsel]
            g = paged_gather(plane, pool_plane, tbl, nbs=nbs_full)
        else:
            g = jnp.take(plane, slots_i, axis=0)
        return g[:, 0, :bound]  # drop the fake head axis

    if not use_kernel:
        Sk = min(skey, S) if skey else S
        if quantized:
            lat = rows_of(cache_c["q"], pool_c and pool_c["q"], Sk)
            rop = rows_of(cache_r["q"], pool_r and pool_r["q"], Sk)
            ls = rows_of(cache_c["s"], pool_c and pool_c["s"], Sk).astype(jnp.float32)
            rs = rows_of(cache_r["s"], pool_r and pool_r["s"], Sk).astype(jnp.float32)
        else:
            lat = rows_of(cache_c, pool_c, Sk)
            rop = rows_of(cache_r, pool_r, Sk)
            ls = rs = None
        return _ragged_attend_mla_fallback(
            qt, qr, c_self, kr_self, lat, rop, ls, rs, rowids, starts, scale
        )

    interp = _interpret() if interpret is None else interpret
    bt = None if block_tables is None else S // block_tables.shape[1]
    BS = ragged_block_size(S, bt)
    tbl, nbs, paged_ = _ragged_tables(slots, S, BS, block_tables)
    # rope rows + dequant scales pre-gathered whole-S (per-block rope/scale
    # slices are the narrow DMAs Mosaic rejects); latent payload streams
    rop_g = rows_of(rop_all, pool_r["q"] if (paged_ and quantized) else pool_r, S)
    if quantized:
        ls_g = rows_of(cache_c["s"], pool_c and pool_c["s"], S)[:, None, :]
        rs_g = rows_of(cache_r["s"], pool_r and pool_r["s"], S)[:, None, :]
    else:
        ls_g = jnp.ones((R, 1, S), jnp.float32)
        rs_g = jnp.ones((R, 1, S), jnp.float32)
    pl_pool = (
        (pool_c["q"] if quantized else pool_c)
        if paged_
        else jnp.zeros((L, 1, 1, BS, Rl), lat_all.dtype)
    )
    BQ = min(block_q, T)
    assert T % BQ == 0, (T, BQ)
    kernel = functools.partial(
        _ragged_prefill_mla_kernel, scale=scale, block_s=BS, seq_len=S, n_rows=R
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(T // BQ,),
        in_specs=[
            pl.BlockSpec((BQ, qt.shape[1], Rl), lambda qi, li, of, st, tb: (qi, 0, 0)),
            pl.BlockSpec((BQ, qt.shape[1], dr), lambda qi, li, of, st, tb: (qi, 0, 0)),
            pl.BlockSpec((T, Rl), lambda qi, li, of, st, tb: (0, 0)),
            pl.BlockSpec((T, dr), lambda qi, li, of, st, tb: (0, 0)),
            pl.BlockSpec((R, S, dr), lambda qi, li, of, st, tb: (0, 0, 0)),
            pl.BlockSpec((R, 1, S), lambda qi, li, of, st, tb: (0, 0, 0)),
            pl.BlockSpec((R, 1, S), lambda qi, li, of, st, tb: (0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),  # latent arena
            pl.BlockSpec(memory_space=pl.ANY),  # latent pool
        ],
        out_specs=pl.BlockSpec(
            (BQ, qt.shape[1], Rl), lambda qi, li, of, st, tb: (qi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, BS, Rl), lat_all.dtype),
            pltpu.SemaphoreType.DMA((2, 1)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, qt.shape[1], Rl), qt.dtype),
        interpret=interp,
    )(
        jnp.reshape(jnp.asarray(layer, jnp.int32), (1,)),
        jnp.asarray(offsets, jnp.int32),
        starts,
        tbl.reshape(-1).astype(jnp.int32),
        qt,
        qr,
        c_self,
        kr_self,
        rop_g,
        ls_g,
        rs_g,
        lat_all,
        pl_pool,
    )
