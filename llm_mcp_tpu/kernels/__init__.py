from .attention import (
    flash_prefill_attention,
    decode_attention,
    pallas_supported,
    resolve_attn_impl,
)

__all__ = [
    "flash_prefill_attention",
    "decode_attention",
    "pallas_supported",
    "resolve_attn_impl",
]
