# One image for every service (core / worker / telemetry / mcp bridge);
# the compose/k8s manifests pick the process via `command:`.
# Role parity: the reference builds one image per service directory
# (compose.yml build contexts); with a single Python package a single
# image is simpler and keeps versions in lockstep.
FROM python:3.12-slim

WORKDIR /app

# TPU hosts: swap the jax extra for the libtpu wheel, e.g.
#   pip install "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
RUN pip install --no-cache-dir \
    "jax[cpu]" flax optax orbax-checkpoint einops \
    grpcio protobuf httpx pyyaml regex tokenizers

COPY pyproject.toml ./
COPY llm_mcp_tpu ./llm_mcp_tpu
COPY scripts ./scripts
COPY config ./config
COPY proto ./proto

ENV PYTHONPATH=/app \
    DB_PATH=/data/llmmcp.sqlite3

VOLUME ["/data"]

# default process: the core API server (overridden per service)
CMD ["python", "-m", "llm_mcp_tpu.api"]
