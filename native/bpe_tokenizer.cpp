// Native BPE tokenizer core: the encode/decode hot path of the TPU executor.
//
// Role: the reference delegates tokenization to Ollama's llama.cpp (C++)
// tokenizer inside an external process; this framework runs tokenization
// in-process, and this library is its native equivalent — the byte-level
// BPE merge loop (O(n^2) in Python, the dominant cost of prefill admission)
// and the streaming UTF-8 boundary scanner used by the SSE token stream.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the build image).
// Cold-path work (tokenizer.json parsing, GPT-2 byte-unicode remapping,
// regex pretokenization) stays in Python; this library owns the per-piece
// merge loop and byte<->id tables.
//
// Build: g++ -O2 -shared -fPIC -o libbpe.so bpe_tokenizer.cpp

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct MergeInfo {
    int32_t rank;
    int32_t merged_id;
};

struct Bpe {
    std::unordered_map<std::string, int32_t> token_to_id;
    std::vector<std::string> id_to_token;       // id -> raw bytes
    std::unordered_map<uint64_t, MergeInfo> merges;  // (left<<32|right) -> info
    int32_t byte_ids[256];                      // single-byte token ids (-1 = absent)
    bool finalized = false;

    Bpe() { std::memset(byte_ids, -1, sizeof(byte_ids)); }
};

inline uint64_t pair_key(int32_t a, int32_t b) {
    return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
           static_cast<uint32_t>(b);
}

}  // namespace

extern "C" {

void* bpe_new() { return new Bpe(); }

void bpe_free(void* h) { delete static_cast<Bpe*>(h); }

// Register a vocab entry: `bytes` is the token's RAW byte string (the Python
// loader undoes GPT-2 byte-to-unicode remapping before calling).
int bpe_add_token(void* h, const uint8_t* bytes, int len, int32_t id) {
    if (h == nullptr || bytes == nullptr || len < 0 || id < 0) return -1;
    Bpe* b = static_cast<Bpe*>(h);
    std::string tok(reinterpret_cast<const char*>(bytes), static_cast<size_t>(len));
    b->token_to_id.emplace(tok, id);
    if (static_cast<size_t>(id) >= b->id_to_token.size()) {
        b->id_to_token.resize(static_cast<size_t>(id) + 1);
    }
    b->id_to_token[static_cast<size_t>(id)] = std::move(tok);
    if (len == 1) b->byte_ids[bytes[0]] = id;
    return 0;
}

// Register a merge rule: (left, right) token ids merge into `merged_id` with
// priority `rank` (lower rank merges first).
int bpe_add_merge(void* h, int32_t left, int32_t right, int32_t rank, int32_t merged_id) {
    if (h == nullptr || left < 0 || right < 0 || merged_id < 0) return -1;
    Bpe* b = static_cast<Bpe*>(h);
    b->merges[pair_key(left, right)] = MergeInfo{rank, merged_id};
    return 0;
}

int bpe_num_tokens(void* h) {
    return h ? static_cast<int>(static_cast<Bpe*>(h)->token_to_id.size()) : 0;
}

// Encode one pretokenized piece (raw bytes) into token ids.
// Returns the number of ids written, or -1 on bad args / overflow of max_out.
// Bytes with no single-byte token are skipped (mirrors ByteTokenizer's
// out-of-range policy: garbage must not crash the stream).
int bpe_encode(void* h, const uint8_t* text, int len, int32_t* out, int max_out) {
    if (h == nullptr || (text == nullptr && len > 0) || out == nullptr || len < 0) return -1;
    Bpe* b = static_cast<Bpe*>(h);

    // initial symbol sequence: one id per byte
    std::vector<int32_t> sym;
    sym.reserve(static_cast<size_t>(len));
    for (int i = 0; i < len; ++i) {
        int32_t id = b->byte_ids[text[i]];
        if (id >= 0) sym.push_back(id);
    }

    // greedy merge loop: repeatedly apply the lowest-rank adjacent pair.
    // Pieces are pretokenized words (tens of bytes), so the quadratic scan
    // beats heap bookkeeping in practice.
    while (sym.size() >= 2) {
        int best_pos = -1;
        int32_t best_rank = INT32_MAX;
        int32_t best_id = -1;
        for (size_t i = 0; i + 1 < sym.size(); ++i) {
            auto it = b->merges.find(pair_key(sym[i], sym[i + 1]));
            if (it != b->merges.end() && it->second.rank < best_rank) {
                best_rank = it->second.rank;
                best_pos = static_cast<int>(i);
                best_id = it->second.merged_id;
            }
        }
        if (best_pos < 0) break;
        sym[static_cast<size_t>(best_pos)] = best_id;
        sym.erase(sym.begin() + best_pos + 1);
    }

    if (static_cast<int>(sym.size()) > max_out) return -1;
    std::memcpy(out, sym.data(), sym.size() * sizeof(int32_t));
    return static_cast<int>(sym.size());
}

// Encode MANY pretokenized pieces in one call (the per-call ctypes overhead
// otherwise dominates: a document is thousands of pieces). `data` is the
// concatenation of all pieces' bytes; `offsets` has n_pieces+1 entries with
// piece i spanning [offsets[i], offsets[i+1]). Returns total ids written,
// or -1 on bad args / output overflow.
int bpe_encode_batch(void* h, const uint8_t* data, const int32_t* offsets,
                     int n_pieces, int32_t* out, int max_out) {
    if (h == nullptr || offsets == nullptr || out == nullptr || n_pieces < 0) return -1;
    Bpe* b = static_cast<Bpe*>(h);
    std::vector<int32_t> sym;
    int w = 0;
    for (int p = 0; p < n_pieces; ++p) {
        int32_t start = offsets[p], end = offsets[p + 1];
        if (start < 0 || end < start) return -1;

        sym.clear();
        sym.reserve(static_cast<size_t>(end - start));
        for (int32_t i = start; i < end; ++i) {
            int32_t id = b->byte_ids[data[i]];
            if (id >= 0) sym.push_back(id);
        }
        while (sym.size() >= 2) {
            int best_pos = -1;
            int32_t best_rank = INT32_MAX;
            int32_t best_id = -1;
            for (size_t i = 0; i + 1 < sym.size(); ++i) {
                auto it = b->merges.find(pair_key(sym[i], sym[i + 1]));
                if (it != b->merges.end() && it->second.rank < best_rank) {
                    best_rank = it->second.rank;
                    best_pos = static_cast<int>(i);
                    best_id = it->second.merged_id;
                }
            }
            if (best_pos < 0) break;
            sym[static_cast<size_t>(best_pos)] = best_id;
            sym.erase(sym.begin() + best_pos + 1);
        }
        if (w + static_cast<int>(sym.size()) > max_out) return -1;
        std::memcpy(out + w, sym.data(), sym.size() * sizeof(int32_t));
        w += static_cast<int>(sym.size());
    }
    return w;
}

// Decode ids back to raw bytes. Unknown ids are skipped. Returns byte count,
// or -1 when the output buffer is too small (call again with a bigger one).
int bpe_decode(void* h, const int32_t* ids, int n, uint8_t* out, int max_out) {
    if (h == nullptr || (ids == nullptr && n > 0) || out == nullptr || n < 0) return -1;
    Bpe* b = static_cast<Bpe*>(h);
    int w = 0;
    for (int i = 0; i < n; ++i) {
        int32_t id = ids[i];
        if (id < 0 || static_cast<size_t>(id) >= b->id_to_token.size()) continue;
        const std::string& tok = b->id_to_token[static_cast<size_t>(id)];
        if (w + static_cast<int>(tok.size()) > max_out) return -1;
        std::memcpy(out + w, tok.data(), tok.size());
        w += static_cast<int>(tok.size());
    }
    return w;
}

// How many trailing bytes of `data` form an INCOMPLETE UTF-8 sequence and
// must be held back by a streaming decoder (0..3). Mirrors
// ByteTokenizer.decode_stream's boundary logic; shared by the SSE stream.
int utf8_hold(const uint8_t* data, int len) {
    if (data == nullptr || len <= 0) return 0;
    int scan = len < 3 ? len : 3;
    for (int i = 1; i <= scan; ++i) {
        uint8_t c = data[len - i];
        if (c < 0x80) return 0;          // ASCII: complete
        if (c >= 0xC0) {                 // lead byte
            int need = c < 0xE0 ? 2 : (c < 0xF0 ? 3 : 4);
            return i < need ? i : 0;
        }
        // else continuation byte: keep scanning backwards
    }
    return 0;
}

}  // extern "C"
